"""Sequence-parallel (sp) axis benchmark — VERDICT r3 next-step #6.

Replays a prefix of the B4 editing trace through `ShardedDoc` at 1 vs 8
shards and measures:

- routed updates/s end-to-end (host router + device YATA per shard);
- `find_position` latency (the O(S) prefix-sum lookup vs the reference's
  O(items) walk, types/text.rs:734 / block.rs:723);
- the per-flush device step cost.

Run: python benches/sp_axis.py [--ops N]. Prints one JSON line per shard
count plus a summary comparing 8-shard to 1-shard throughput. CPU or TPU
(whatever backend jax resolves; the capture labels it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# an 8-way host-device mesh lets the sp axis ACTUALLY partition when the
# backend is CPU (each virtual device gets an XLA thread — real speedup
# on multi-core boxes; harmless on 1 vCPU). Must precede the first jax
# import. On TPU the flag is ignored (it only affects the host platform).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

from _env import repin_jax_platforms  # noqa: E402

repin_jax_platforms()


def b4_prefix_updates(n_ops: int):
    import bench as bench_mod

    if os.path.exists(bench_mod.TRACE_PATH):
        ops = bench_mod.load_b4_ops(n_ops)
    else:
        ops = bench_mod.synthetic_ops(n_ops)
    return bench_mod.build_updates(ops)


def run_shards(log, expect, n_shards: int, capacity: int = 8192) -> dict:
    import jax

    from ytpu.parallel.sharded_doc import ShardedDoc

    sd = ShardedDoc(n_shards=n_shards, capacity=capacity)
    mesh_devices = 0
    if n_shards > 1 and len(jax.devices()) >= n_shards:
        import numpy as _np
        from jax.sharding import Mesh

        mesh = Mesh(_np.array(jax.devices()[:n_shards]), ("sp",))
        sd.place_on_mesh(mesh)
        mesh_devices = n_shards
    # warm phase: the first ~half of the trace pays the jit compiles for
    # the flush bucket shapes (and any capacity growth); the steady phase
    # is the serving-regime number (flushes are async since round 5 —
    # host routing overlaps the device steps, `_sync` only at reads)
    warm = len(log) // 2
    t0 = time.perf_counter()
    for p in log[:warm]:
        sd.apply_update_v1(p)
    sd.flush()
    sd._sync()
    warm_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for p in log[warm:]:
        sd.apply_update_v1(p)
    sd.flush()
    sd._sync()
    dt = time.perf_counter() - t0
    n_steady = len(log) - warm
    got = sd.get_string()
    assert got == expect, f"sp replay mismatch: {got[:40]!r} != {expect[:40]!r}"

    # find_position: prefix-sum lookup cost over the final doc
    lens = sd.shard_lengths()  # warm the cached pull
    total = int(lens.sum())
    t0 = time.perf_counter()
    n_lookups = 200
    for i in range(n_lookups):
        sd.find_position((i * 37) % max(1, total))
    pos_dt = (time.perf_counter() - t0) / n_lookups
    return {
        "metric": f"sp{n_shards}_updates_per_sec",
        "value": round(n_steady / dt, 1),
        "unit": f"steady-state routed updates/s, {n_shards}-shard "
        f"ShardedDoc ({n_steady} of {len(log)} B4-prefix updates; "
        "first half warms the jit buckets)",
        "cold_updates_per_sec": round(warm / warm_dt, 1),
        "find_position_us": round(1e6 * pos_dt, 1),
        "doc_units": total,
        "platform": jax.devices()[0].platform,
        "mesh_devices": mesh_devices,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=2000)
    args = ap.parse_args()
    log, expect = b4_prefix_updates(args.ops)
    # size capacity to the trace up front: mid-run growth recompiles the
    # apply program (~seconds each on CPU) and was the real reason the
    # round-4 capture read tens of updates/s
    cap = 1 << (max(2048, 4 * args.ops) - 1).bit_length()
    out = []
    for s in (1, 8):
        # capacity is PER SHARD: the segments partition the doc, so each
        # shard's columns need ~1/S of the total (2x headroom for skew)
        per_shard = 1 << (max(1024, 2 * cap // s) - 1).bit_length()
        r = run_shards(log, expect, s, capacity=per_shard)
        out.append(r)
        print(json.dumps(r), flush=True)
    print(
        json.dumps(
            {
                "metric": "sp_axis_8v1_speedup",
                "value": round(out[1]["value"] / out[0]["value"], 3),
                "unit": "8-shard / 1-shard routed updates/s "
                "(host router shared; device YATA parallel over sp)",
                "find_position_us_8": out[1]["find_position_us"],
                "find_position_us_1": out[0]["find_position_us"],
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
