#!/usr/bin/env python
"""Diff two bench one-line JSON captures field by field (ISSUE-11).

Every bench round emits one JSON line (`bench.py`, committed as
`BENCH_r*.json`), but comparing rounds has been eyeball work — and the
ROADMAP's "no worse than" criteria have no mechanical check. This tool
is that check::

    python benches/bench_compare.py BENCH_r04.json BENCH_r05.json
    python benches/bench_compare.py old.json new.json --tol value=0.25
    python benches/bench_compare.py a.json b.json --default-tol 0.15
    python benches/bench_compare.py --trend candidate.json

``--trend`` (ISSUE-17) drops the explicit baseline: the candidate is
diffed against a synthetic **best-ever** capture folded from every
committed ``BENCH_r*.json`` with the candidate's platform tag (max over
history for higher-is-better keys, min for lower-is-better) — a round
that merely beats LAST round but falls short of the repo's best is
still called out.

Semantics:

- both files hold one JSON object (a bench one-line capture; a file
  with multiple lines uses its LAST non-empty line, matching how bench
  output is teed into logs);
- nested dicts flatten to dotted keys (``soak.rounds``,
  ``phases.host.replay.execute_s``); only numeric leaves compare —
  strings/bools are checked for equality and reported (never a
  regression: units and notes legitimately drift);
- a numeric change beyond tolerance is a **regression** only when the
  key's direction is known: higher-is-better keys (throughput, speedups,
  ``vs_*`` ratios) regress when B < A, lower-is-better keys (latency
  ``*_ms`` / ``*_s`` quantiles) regress when B > A. Unknown-direction
  numeric drift is reported as NEUTRAL and never fails the run —
  exactly like a human reviewer treats `chunks` changing.
- exit code: 0 = no regression, 1 = ≥1 regression, 2 = usage/load error.

`--json` emits the full diff as one JSON line (for tooling); default
output is a human-readable table of changed fields.

The tool itself is gated: `tests/test_bench_compare.py` pins direction/
tolerance semantics on synthetic captures, and a slow-marked test runs a
real `bench.py --dry-run` and asserts self-comparison is a zero diff
with exit 0.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

__all__ = [
    "flatten",
    "classify",
    "compare",
    "load_capture",
    "capture_surface",
    "capture_platform",
    "repo_captures",
    "trend_baseline",
    "main",
]

#: default relative tolerance for numeric fields (|b-a| / max(|a|,eps))
DEFAULT_REL_TOL = 0.10

#: key-substring → direction. First match wins (checked in order), so
#: more specific fragments come first. "up" = higher is better, "down" =
#: lower is better. Everything else is neutral: reported, never failing.
_DIRECTION_RULES: Tuple[Tuple[str, str], ...] = (
    # unified wall-time attribution (ISSUE-17): the profile_* fractions
    # are a COMPOSITION of the wall budget, not better/worse — device
    # fraction legitimately falls when staging gets faster. Pinned
    # neutral FIRST so `profile_stall_fraction` never hits the
    # directional stall_fraction rule below.
    ("profile_", "neutral"),
    ("fractions_sum", "neutral"),
    ("stall_fraction", "down"),
    # compile/retrace sentinel (ISSUE-17): on the same warmed workload,
    # more retraces or more cumulative trace seconds is a regression —
    # a shape/static-plan leak re-entered the jit boundary. (Leaf
    # "retraces" also catches the compile_retraces headline.)
    ("retraces", "down"),
    # scan_iterations_total is workload shape (its leaf would otherwise
    # substring-match "s_total"); cumulative TRACE seconds regress on rise
    ("scan_iterations_total", "neutral"),
    ("s_total", "down"),
    ("_per_s", "up"),
    ("_per_sec", "up"),
    ("updates_per_s", "up"),
    ("speedup", "up"),
    ("overlap_ratio", "up"),
    ("vs_baseline", "up"),
    ("vs_native", "up"),
    ("vs_py_oracle", "up"),
    ("scan_trip_reduction", "up"),  # two-tier dispatch compression factor
    ("scan_width", "down"),  # conflict-scan tail: narrower is better
    # two-tier scan dispatch-trip counts (ISSUE-12): like latency, a
    # rise on the same workload is a regression — more serial while
    # trips per integrate. (Tier OCCUPANCY `scan_tier_*` stays neutral:
    # the cheap/wide split is workload shape, not better/worse.)
    ("scan_trips", "down"),
    # federation (ISSUE-13): rounds-to-byte-agreement and anti-entropy
    # traffic are costs — a rise on the same scenario is a regression
    # (more rounds / more bytes to reach the same converged state).
    # Occupancy-style counts (partitions, heals, mismatches) stay
    # neutral: they are the scripted chaos schedule, not better/worse.
    ("converge_rounds", "down"),
    ("anti_entropy_bytes", "down"),
    # autopilot on-vs-off deltas (ISSUE-16): availability_delta = on −
    # off (shrinking toward 0 means the controller stopped winning →
    # regresses on DROP); p99_adj_delta = on − off ms (negative is the
    # win; a RISE toward 0 is a regression). Raw action counts stay
    # neutral: more actions is a policy choice, not better/worse.
    ("availability_delta", "up"),
    ("p99_adj_delta", "down"),
    # capacity observatory (ISSUE-18): device-memory use regresses on
    # RISE (memory_peak_bytes, memory.program_bytes leaves), while the
    # forecaster's headroom and the doc-axis ceiling regress on DROP —
    # a smaller survivable doc axis or thinner headroom is the ceiling
    # closing in. The configured budget is an input, not a measurement,
    # so it pins neutral BEFORE the broad memory_ rule; occupancy /
    # fragmentation gauges (dead_rows, live_rows, dead_fraction,
    # reclaimed_rows, compact_gap_chunks) stay neutral by default —
    # they are workload shape, like the scan-tier occupancy split.
    ("headroom_fraction", "up"),
    ("doc_ceiling", "up"),
    # doc-axis sub-batching (ISSUE-20): a narrowed sub-batch width is
    # the memory budget closing in mid-replay — `subbatch_narrowed`
    # regresses on RISE. The width itself and the scaling ratio are
    # configuration/workload shape, not better/worse (the single-device
    # CPU ratio is an overhead floor, the mesh path the speedup axis):
    # both pin neutral, with the narrowed rule FIRST so its leaf never
    # falls through to the neutral `subbatch_` catch-all.
    ("subbatch_narrowed", "down"),
    ("sub_batch_scaling", "neutral"),
    ("subbatch_", "neutral"),
    ("memory_budget", "neutral"),
    ("memory_", "down"),
    ("peak_bytes", "down"),
    ("p50_ms", "down"),
    ("p99_ms", "down"),
    ("p999_ms", "down"),
    ("max_ms", "down"),
    ("rtt_floor_ms", "down"),
    ("_dt", "down"),
    ("p99_chunk_ms", "down"),
    ("p50_apply_ms", "down"),
    ("p99_apply_ms", "down"),
)

#: keys whose drift is pure noise at small scales — compared with a wider
#: default tolerance unless the caller overrides per key
_NOISY_DEFAULTS = {
    "rtt_floor_ms": 1.0,  # scheduler noise floor on loopback
    "wall_s": 1.0,
}


#: exact flattened keys with a known direction (the bench headline is
#: literally called "value"; a substring rule would misfire on the
#: phases gauges that also flatten to `.value` leaves)
_FULL_KEY_DIRECTION = {"value": "up", "parsed.value": "up"}


def classify(key: str) -> str:
    """'up' | 'down' | 'neutral' for a flattened key."""
    d = _FULL_KEY_DIRECTION.get(key)
    if d is not None:
        return d
    leaf = key.rsplit(".", 1)[-1]
    for frag, direction in _DIRECTION_RULES:
        if frag in leaf:
            return direction
    return "neutral"


def flatten(obj, prefix: str = "") -> Dict[str, object]:
    """Nested dicts → dotted scalar leaves. Lists compare as JSON text
    (order is meaningful in bench captures, e.g. `tunnel_queue`)."""
    out: Dict[str, object] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(flatten(v, f"{prefix}{k}."))
    elif isinstance(obj, list):
        out[prefix[:-1]] = json.dumps(obj)
    else:
        out[prefix[:-1]] = obj
    return out


def compare(
    a: Dict,
    b: Dict,
    tolerances: Optional[Dict[str, float]] = None,
    default_rel: float = DEFAULT_REL_TOL,
) -> Dict:
    """Field-by-field diff of two captures. Returns
    ``{"regressions": [...], "improvements": [...], "changes": [...],
    "added": [...], "removed": [...]}`` where each entry is a dict with
    key / a / b / rel_change / direction."""
    tolerances = dict(tolerances or {})
    fa, fb = flatten(a), flatten(b)
    regressions: List[Dict] = []
    improvements: List[Dict] = []
    changes: List[Dict] = []
    added = sorted(set(fb) - set(fa))
    removed = sorted(set(fa) - set(fb))
    for key in sorted(set(fa) & set(fb)):
        va, vb = fa[key], fb[key]
        if isinstance(va, bool) or isinstance(vb, bool) or not (
            isinstance(va, (int, float)) and isinstance(vb, (int, float))
        ):
            if va != vb:
                changes.append(
                    {"key": key, "a": va, "b": vb, "direction": "neutral"}
                )
            continue
        if va == vb:
            continue
        leaf = key.rsplit(".", 1)[-1]
        tol = tolerances.get(
            key, tolerances.get(leaf, _NOISY_DEFAULTS.get(leaf, default_rel))
        )
        rel = (vb - va) / max(abs(va), 1e-12)
        entry = {
            "key": key,
            "a": va,
            "b": vb,
            "rel_change": round(rel, 4),
            "direction": classify(key),
            "tol": tol,
        }
        if abs(rel) <= tol:
            continue  # within tolerance: not even a change worth listing
        if entry["direction"] == "up":
            (regressions if rel < 0 else improvements).append(entry)
        elif entry["direction"] == "down":
            (regressions if rel > 0 else improvements).append(entry)
        else:
            changes.append(entry)
    return {
        "regressions": regressions,
        "improvements": improvements,
        "changes": changes,
        "added": added,
        "removed": removed,
    }


def capture_surface(d: Dict) -> Dict:
    """The measurement surface of a committed artifact: end-of-round
    ``BENCH_r*.json`` wrap the bench one-line JSON under ``parsed``;
    midsession captures ARE the surface. The bulky phases/metrics blobs
    are stripped — trend verdicts regress headlines, not trace dumps."""
    cap = d.get("parsed") if isinstance(d.get("parsed"), dict) else d
    return {k: v for k, v in cap.items() if k not in ("phases", "metrics")}


def capture_platform(d: Dict) -> str:
    """First word of the capture's platform tag (``"cpu (1 vCPU)"`` →
    ``"cpu"``), defaulting to ``host`` — the series key the trajectory
    ledger uses, so trend baselines never mix hardware with host runs."""
    return str(capture_surface(d).get("platform") or "host").split()[0]


def repo_captures(directory: Optional[str] = None) -> List[Tuple[Tuple, Dict]]:
    """Every loadable committed ``BENCH_r*.json`` as (rank, raw dict),
    oldest round first. Rank mirrors `bench._capture_rank`: round number
    from the filename, then the in-capture timestamp (mtime is useless —
    a git checkout stamps every artifact at once)."""
    import glob
    import os
    import re

    if directory is None:
        directory = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
    out = []
    for path in glob.glob(os.path.join(directory, "BENCH_r*.json")):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
        rank = (
            int(m.group(1)) if m else -1,
            str(d.get("captured_at") or ""),
        )
        out.append((rank, d))
    return sorted(out, key=lambda t: t[0])


def trend_baseline(captures: List[Dict]) -> Dict[str, object]:
    """Synthetic FLATTENED baseline for ``--trend`` (ISSUE-17): for every
    directional numeric leaf across the captures, the BEST value ever
    recorded (max for "up" keys, min for "down"); neutral and
    non-numeric keys keep the newest capture's value. Comparing a
    candidate against this regresses it against the repo's best-ever
    trajectory point, not just whatever round happened to land last."""
    base: Dict[str, object] = {}
    for cap in captures:  # oldest → newest, so "newest wins" is last-write
        for k, v in flatten(cap).items():
            numeric = isinstance(v, (int, float)) and not isinstance(v, bool)
            prior = base.get(k)
            prior_numeric = isinstance(prior, (int, float)) and not isinstance(
                prior, bool
            )
            if not (numeric and prior_numeric):
                base[k] = v
                continue
            d = classify(k)
            if d == "up":
                base[k] = max(prior, v)
            elif d == "down":
                base[k] = min(prior, v)
            else:
                base[k] = v
    return base


def load_capture(path: str) -> Dict:
    """One JSON object from `path` — a `BENCH_*.json` capture or any log
    whose LAST non-empty line is the bench one-line JSON."""
    with open(path) as f:
        text = f.read().strip()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        lines = [ln for ln in text.splitlines() if ln.strip()]
        if not lines:
            raise
        return json.loads(lines[-1])


def _render(diff: Dict, a_name: str, b_name: str) -> str:
    rows = []
    for kind, entries in (
        ("REGRESSION", diff["regressions"]),
        ("improvement", diff["improvements"]),
        ("change", diff["changes"]),
    ):
        for e in entries:
            rel = e.get("rel_change")
            rel_s = f"{rel * 100:+.1f}%" if isinstance(rel, float) else ""
            rows.append(
                f"{kind:<12} {e['key']:<48} {e['a']!r:>16} -> "
                f"{e['b']!r:<16} {rel_s}"
            )
    for k in diff["added"]:
        rows.append(f"{'added':<12} {k}")
    for k in diff["removed"]:
        rows.append(f"{'removed':<12} {k}")
    head = (
        f"bench_compare: A={a_name} B={b_name} — "
        f"{len(diff['regressions'])} regression(s), "
        f"{len(diff['improvements'])} improvement(s), "
        f"{len(diff['changes'])} neutral change(s)"
    )
    return "\n".join([head] + rows)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "a",
        help="baseline capture (JSON file); with --trend, the CANDIDATE",
    )
    p.add_argument(
        "b",
        nargs="?",
        default=None,
        help="candidate capture (JSON file); omitted with --trend",
    )
    p.add_argument(
        "--trend",
        action="store_true",
        help="regress the candidate against the best-ever committed "
        "BENCH_r*.json values for its platform tag instead of one "
        "explicit baseline",
    )
    p.add_argument(
        "--captures-dir",
        default=None,
        metavar="DIR",
        help="where --trend looks for BENCH_r*.json (default: repo root)",
    )
    p.add_argument(
        "--tol",
        action="append",
        default=[],
        metavar="KEY=FRAC",
        help="per-key relative tolerance (key may be a flattened key or "
        "a leaf name); repeatable",
    )
    p.add_argument(
        "--default-tol",
        type=float,
        default=DEFAULT_REL_TOL,
        help=f"relative tolerance for keys without a --tol "
        f"(default {DEFAULT_REL_TOL})",
    )
    p.add_argument(
        "--json", action="store_true", help="emit the diff as one JSON line"
    )
    args = p.parse_args(argv)
    tolerances: Dict[str, float] = {}
    for spec in args.tol:
        if "=" not in spec:
            print(f"bad --tol {spec!r} (want KEY=FRAC)", file=sys.stderr)
            return 2
        k, v = spec.split("=", 1)
        try:
            tolerances[k] = float(v)
        except ValueError:
            print(f"bad --tol fraction {v!r}", file=sys.stderr)
            return 2
    if args.trend:
        cand_path = args.b or args.a
        try:
            cand_raw = load_capture(cand_path)
        except (OSError, json.JSONDecodeError) as e:
            print(f"load error: {e}", file=sys.stderr)
            return 2
        cand = capture_surface(cand_raw)
        platform = capture_platform(cand_raw)
        history = [
            capture_surface(d)
            for _, d in repo_captures(args.captures_dir)
            if capture_platform(d) == platform
        ]
        history = [h for h in history if h]
        if not history:
            print(
                f"--trend: no committed BENCH_r*.json with platform "
                f"{platform!r} to fold a baseline from",
                file=sys.stderr,
            )
            return 2
        a, b = trend_baseline(history), cand
        a_name = f"<best-ever:{platform}:{len(history)} captures>"
    elif args.b is None:
        print("candidate capture missing (or use --trend)", file=sys.stderr)
        return 2
    else:
        try:
            a = load_capture(args.a)
            b = load_capture(args.b)
        except (OSError, json.JSONDecodeError) as e:
            print(f"load error: {e}", file=sys.stderr)
            return 2
        a_name = args.a
    diff = compare(a, b, tolerances, args.default_tol)
    if args.json:
        print(json.dumps(diff))
    else:
        print(_render(diff, a_name, args.b or args.a))
    return 1 if diff["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
