#!/bin/bash
# Round-5 recovery capture: when the tunnel returns, run the full
# crash-ordered bench (flagship-first, fused config lanes last) and then
# the fused-vs-xla prefix ratio. One healthy window lands everything.
cd /root/repo
while true; do
  if timeout 60 python -c "import jax, jax.numpy as j; j.ones((4,4)).sum().block_until_ready()" 2>/dev/null; then
    echo "$(date +%H:%M:%S) TUNNEL UP - full bench" >> benches/recovery_capture.log
    YTPU_BENCH_DEVICE_TIMEOUT=5400 timeout 7200 python bench.py \
      > benches/bench_recovery.out 2>&1
    tail -1 benches/bench_recovery.out > BENCH_r05_midsession2.json
    echo "$(date +%H:%M:%S) bench done - fused_vs_xla_prefix" >> benches/recovery_capture.log
    timeout 3600 python benches/fused_vs_xla_prefix.py 160000 64 \
      > benches/fused_vs_xla_prefix.log 2>&1
    echo "$(date +%H:%M:%S) all done" >> benches/recovery_capture.log
    exit 0
  fi
  echo "$(date +%H:%M:%S) down" >> benches/recovery_capture.log
  sleep 90
done
