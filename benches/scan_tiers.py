"""Two-tier conflict-scan microbench (ISSUE-12, ROADMAP item 2).

The serial `lax.while_loop` dispatch of the YATA conflict scan — one
candidate per trip — owned the p99 integrate tail (width p50=32 /
p99=337 on the 256-client concurrent workload; the origin_slot cache
bought only +1.6%, VERDICT Weak #6). The two-tier scan keeps the
original loop as a bounded CHEAP tier and resolves the deep-conflict
tail in a vectorized WIDE tier (fixed unroll over the packed columns:
`unroll` masked candidate steps per while trip). This bench builds two
adversarial streams and measures the split:

- **p50-shaped**: modest concurrency (`P50_SHAPE` = 4 clients × 6
  same-origin inserts) — every scan must resolve inside the cheap
  tier, trip cost identical to the pre-ISSUE-12 loop (no regression on
  the mass).
- **p99-shaped**: deep concurrency (`P99_SHAPE` = 48 clients × 24
  inserts at ONE origin, ~1.1k concurrent same-origin siblings) — the
  wide tier must fire and compress the dispatch-trip count ≥ 4× vs the
  serial-equivalent loop, at byte parity with the host oracle.

Trip accounting is MEASURED, not modeled: the integrate lanes fold
`Σ width` (what the one-candidate-per-trip loop would have dispatched)
and `Σ min(width, cheap) + Σ wide-tier blocks` (what the two-tier
dispatch actually pays) into the meta record that rides the lazy
readout (`ReplayChunkStats.scan_trips_serial` / `scan_trips_two_tier`).

Modes:
- CPU (or `--dry-run`): asserts the TIER PLAN + trip compression +
  oracle parity on the packed-XLA lane. No device work; runs in CI as
  the `scan_tiers` leg of `bench.py --dry-run`.
- hardware: additionally times the per-update integrate step on the
  fused lane for both streams (the p99/p50 step-ratio headline).

Usage: python benches/scan_tiers.py [--dry-run]
Artifact: benches/scan_tiers.json
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if HERE not in sys.path:
    sys.path.insert(0, HERE)

OUT = os.path.join(HERE, "benches", "scan_tiers.json")

#: stream shapes: (n_clients, inserts_each). p50 keeps every width under
#: the default cheap bound (32); p99 builds ~1.1k concurrent same-origin
#: siblings so scan widths ramp deep into the wide tier (measured
#: reduction 4.6x at the default (32, 8) plan — the ramp dilutes the
#: per-scan compression, so the stream must overshoot the p99=337
#: target width for the AGGREGATE to clear 4x).
P50_SHAPE = (4, 6)
P99_SHAPE = (48, 24)
#: the acceptance floor: serial-equivalent while trips / two-tier trips
#: on the p99-shaped stream (ISSUE-12 acceptance says >= 4x)
MIN_TRIP_REDUCTION = 4.0


def build_conflict_stream(n_clients: int, inserts_each: int,
                          erase_every: int = 4, rounds: int = 1,
                          typed: bool = False, erase_len: int = 2):
    """N concurrent clients all inserting at ONE origin position of a
    shared base text — the YATA worst case: every integration scans the
    other clients' already-integrated same-origin siblings. Clients
    never see each other pre-merge (scenario-grammar style), so the
    converged text is interleave-independent and the host oracle is the
    byte-parity surface.

    Knobs (the ONE generator shared by this bench and
    tests/test_scan_tiers.py, so the acceptance stream and the parity
    stream can never drift apart): `erase_every > 0` has every
    erase_every-th client delete `erase_len` chars of its round's
    inserts; `typed=True` types rightward (insert at 5, 6, 7, ... —
    ascending clocks, sequence-adjacent) so the erased runs are the
    shape `compact_packed` can merge and reclaim (the default
    stack-order inserts at one position produce DESCENDING-clock runs
    whose tombstones cannot merge); conflict depth survives `typed`
    because each run's FIRST insert still anchors on the shared base
    origin and scans every other client's run.

    Returns ``(payloads, expect_text)``: the merge-order payload list
    (base first, then round-robin across clients so the conflict set
    grows as wide as possible) and the host-oracle converged text."""
    from ytpu.core import Doc

    def capture(doc):
        log = []
        doc.observe_update_v1(lambda p, o, t: log.append(p))
        return log

    base = Doc(client_id=1)
    base_log = capture(base)
    txt = base.get_text("text")
    with base.transact() as txn:
        txt.insert(txn, 0, "0123456789")
    base_update = base.encode_state_as_update_v1()

    per_client = []
    for k in range(n_clients):
        doc = Doc(client_id=10 + k)
        doc.apply_update_v1(base_update)
        log = capture(doc)
        t = doc.get_text("text")
        for _ in range(rounds):
            for i in range(inserts_each):
                with doc.transact() as txn:
                    t.insert(txn, 5 + (i if typed else 0),
                             "abcdefgh"[(k + i) % 8])
            if erase_every and k % erase_every == 0:
                # interleaved deletes: tombstones inside the conflict
                # neighborhood (the scan walks deleted rows too)
                with doc.transact() as txn:
                    t.remove_range(txn, 5, erase_len)
        per_client.append(log)

    payloads = list(base_log)
    for i in range(max(len(log) for log in per_client)):
        for log in per_client:
            if i < len(log):
                payloads.append(log[i])

    oracle = Doc(client_id=2)
    for p in payloads:
        oracle.apply_update_v1(p)
    return payloads, oracle.get_text("text").get_string()


def replay_xla(payloads, capacity: int, chunk: int = 16, n_docs: int = 1):
    """Replay through the packed-XLA chunked lane; returns
    ``(decoded_texts, ReplayChunkStats)``."""
    from ytpu.core import Update
    from ytpu.models.batch_doc import BatchEncoder, get_string, init_state
    from ytpu.ops.integrate_kernel import replay_stream_fused

    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in payloads]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    st, stats = replay_stream_fused(
        init_state(n_docs, capacity),
        stream,
        rank,
        chunk_steps=chunk,
        lane="xla",
        max_capacity=capacity * 4,
    )
    import numpy as np

    assert int(np.asarray(st.error).max()) == 0, "device error flags set"
    texts = [get_string(st, d, enc.payloads) for d in range(n_docs)]
    return texts, stats


def assert_tier_plan(stats_p50, stats_p99, scan_plan) -> dict:
    """The CPU-checkable ISSUE-12 contract, from MEASURED trip words."""
    cheap_bound, unroll = scan_plan
    out = {
        "cheap_bound": cheap_bound,
        "wide_unroll": unroll,
        "p50": _tier_dict(stats_p50),
        "p99": _tier_dict(stats_p99),
    }
    # p50 mass: the cheap tier carries it — no wide escalation, and the
    # two-tier dispatch pays EXACTLY the serial trip count (zero
    # regression on shallow scans)
    assert stats_p50.scan_tier_cheap > 0, stats_p50
    if stats_p50.scan_max < max(cheap_bound, 1):
        assert stats_p50.scan_tier_wide == 0, stats_p50
        assert (
            stats_p50.scan_trips_two_tier == stats_p50.scan_trips_serial
        ), stats_p50
    # p99 tail: the wide tier fires and compresses dispatch trips
    assert stats_p99.scan_tier_wide > 0, stats_p99
    assert stats_p99.scan_max > cheap_bound, stats_p99
    reduction = stats_p99.scan_trips_serial / max(
        1, stats_p99.scan_trips_two_tier
    )
    out["p99"]["trip_reduction"] = round(reduction, 2)
    out["scan_trip_reduction"] = round(reduction, 2)
    assert reduction >= MIN_TRIP_REDUCTION, (
        f"p99-shaped dispatch-trip reduction {reduction:.2f}x < "
        f"{MIN_TRIP_REDUCTION}x (serial {stats_p99.scan_trips_serial} vs "
        f"two-tier {stats_p99.scan_trips_two_tier})"
    )
    return out


def _tier_dict(stats) -> dict:
    return {
        "scan_tier_cheap": stats.scan_tier_cheap,
        "scan_tier_wide": stats.scan_tier_wide,
        "scan_trips_serial": stats.scan_trips_serial,
        "scan_trips_two_tier": stats.scan_trips_two_tier,
        "scan_width_p50": stats.scan_p50,
        "scan_width_p99": stats.scan_p99,
        "scan_width_max": stats.scan_max,
    }


def dry_run() -> dict:
    """The `bench.py --dry-run` leg: tier plan + trip compression +
    oracle parity on the packed-XLA lane, CPU only."""
    from ytpu.models.batch_doc import scan_tier_plan

    plan = scan_tier_plan()
    p50_payloads, p50_expect = build_conflict_stream(*P50_SHAPE)
    p99_payloads, p99_expect = build_conflict_stream(*P99_SHAPE)
    texts50, stats50 = replay_xla(p50_payloads, capacity=256)
    texts99, stats99 = replay_xla(p99_payloads, capacity=2048)
    for t in texts50:
        assert t == p50_expect, "p50 stream lost byte parity vs host oracle"
    for t in texts99:
        assert t == p99_expect, "p99 stream lost byte parity vs host oracle"
    out = assert_tier_plan(stats50, stats99, plan)
    out["p50"]["updates"] = len(p50_payloads)
    out["p99"]["updates"] = len(p99_payloads)
    out["parity"] = "ok"
    return out


def device_run(reps: int = 3) -> dict:
    """Hardware mode: per-update integrate-step wall time, fused lane,
    p50- vs p99-shaped streams (the tail-compression headline)."""
    from ytpu.core import Update
    from ytpu.models.batch_doc import BatchEncoder, init_state
    from ytpu.ops.integrate_kernel import replay_stream_fused

    out = {}
    for name, shape, cap in (
        ("p50", P50_SHAPE, 256),
        ("p99", P99_SHAPE, 2048),
    ):
        payloads, _ = build_conflict_stream(*shape)
        enc = BatchEncoder()
        steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in payloads]
        stream = BatchEncoder.stack_steps(steps)
        rank = enc.interner.rank_table()

        def once():
            st, stats = replay_stream_fused(
                init_state(8, cap),
                stream,
                rank,
                chunk_steps=16,
                d_block=8,
                lane="fused",
                max_capacity=cap * 4,
            )
            import jax

            jax.block_until_ready(st.n_blocks)
            return stats

        stats = once()  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            stats = once()
            best = min(best, time.perf_counter() - t0)
        out[name] = {
            "updates": len(payloads),
            "best_wall_s": round(best, 4),
            "us_per_update": round(1e6 * best / len(payloads), 2),
            **_tier_dict(stats),
        }
    if "p50" in out and "p99" in out:
        out["p99_vs_p50_step_ratio"] = round(
            out["p99"]["us_per_update"] / max(1e-9, out["p50"]["us_per_update"]),
            3,
        )
    return out


def main() -> int:
    dry = "--dry-run" in sys.argv[1:]
    state = {"bench": "scan_tiers", "ts": time.strftime("%Y-%m-%d %H:%M:%S")}
    t0 = time.perf_counter()
    state["dry_run"] = dry_run()
    state["dry_run_wall_s"] = round(time.perf_counter() - t0, 2)
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    state["platform"] = jax.devices()[0].platform
    if not dry and on_tpu:
        state["device"] = device_run()
    elif not dry:
        state["mode"] = "cpu (tier plan + parity asserted; no device timing)"
    with open(OUT + ".tmp", "w") as f:
        json.dump(state, f, indent=1)
    os.replace(OUT + ".tmp", OUT)
    print(json.dumps(state))
    return 0


if __name__ == "__main__":
    sys.exit(main())
