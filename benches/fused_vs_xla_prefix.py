"""Fused vs XLA lane on an identical long-B4-prefix workload (hardware).

The fused kernel became silicon-correct on 2026-08-01 (aliased-output
init fix; byte-exact vs the XLA lane, benches/rung9_bisect.json), but a
full-B4 tile needs C=65536 — a ~54MB block the axon Pallas backend
refuses/hangs on. C=32768 (27MB) is in the proven-legal family and holds
a deep prefix of the trace, so the honest fused evidence is a same-config
ratio: both lanes replay the SAME prefix at docs x 32768, fused first
(fresh worker), then xla.

Usage: python benches/fused_vs_xla_prefix.py [n_updates] [docs]
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

OUT = os.path.join(HERE, "benches", "fused_vs_xla_prefix.json")
state: dict = {}


def flush():
    with open(OUT, "w") as f:
        json.dump(state, f, indent=1)


def main() -> int:
    n_updates = int(sys.argv[1]) if len(sys.argv) > 1 else 160_000
    docs = int(sys.argv[2]) if len(sys.argv) > 2 else 64

    os.environ.setdefault("YTPU_BENCH_FULL_DOCS", str(docs))
    os.environ.setdefault("YTPU_BENCH_FULL_CAP0", "32768")
    os.environ.setdefault("YTPU_BENCH_FULL_MAXCAP", "32768")
    os.environ.setdefault("YTPU_BENCH_FULL_DBLOCK", "8")
    os.environ.setdefault("YTPU_FUSED_VMEM_MB", "100")

    spec = importlib.util.spec_from_file_location(
        "ytpu_bench_main", os.path.join(HERE, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    full_log, _, trace = bench.load_full_log()
    log = full_log[:n_updates]
    _, expect = bench.host_replay(log)

    import jax

    state.update(
        platform=jax.devices()[0].platform,
        trace=f"{trace}[:{n_updates}]",
        docs=docs,
        capacity=32768,
    )
    flush()

    for lane in ("fused", "xla"):
        t0 = time.time()
        try:
            r = bench.device_replay_full(log, expect, lane=lane)
            rate = len(log) * r["full_docs"] / r["full_dt"]
            state[lane] = {
                "updates_per_sec": round(rate, 1),
                **{k: (round(v, 2) if isinstance(v, float) else v) for k, v in r.items()},
            }
        except Exception as e:  # noqa: BLE001
            state[lane] = {"error": f"{type(e).__name__}: {e}"[:300]}
        state[lane]["wall_s"] = round(time.time() - t0, 1)
        flush()
    if "updates_per_sec" in state.get("fused", {}) and "updates_per_sec" in state.get(
        "xla", {}
    ):
        state["fused_vs_xla"] = round(
            state["fused"]["updates_per_sec"] / state["xla"]["updates_per_sec"], 2
        )
    flush()
    print(json.dumps(state))
    return 0


if __name__ == "__main__":
    sys.exit(main())
