"""Shared bench-process environment guards."""

from __future__ import annotations

import os


def repin_jax_platforms() -> None:
    """Re-pin jax_platforms from the JAX_PLATFORMS env var.

    The axon site-hook force-updates jax_platforms to "axon,cpu" at
    interpreter start, overriding the env var; when the TPU tunnel hangs
    (rather than failing fast) that blocks jax.devices() forever even for
    CPU-only runs. config.update beats the hook's value — same fix as
    tests/conftest.py. No-op when JAX_PLATFORMS is unset (hardware runs
    WANT the axon backend)."""
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
