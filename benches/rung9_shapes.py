"""Shape sweep for the fused kernel's silicon divergence (rung 9).

rung9_bisect.py found full-column divergence at n_ops=1: the fused lane
writes slot0.left = 0 (a self-pointer -> the walk cycle) and plane-
shifted garbage at slot C-128 on hardware, while interpret mode is
byte-identical. This sweeps (C, d_block, n_docs) on a 1-op stream to map
which tile shapes miscompile.

Usage: python benches/rung9_shapes.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

OUT = os.path.join(HERE, "benches", "rung9_shapes.json")
state: dict = {"cases": {}}


def flush():
    with open(OUT, "w") as f:
        json.dump(state, f, indent=1)


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    state["platform"] = jax.devices()[0].platform
    flush()

    from ytpu.core import Doc
    from ytpu.models.batch_doc import apply_update_stream, init_state
    from ytpu.ops.decode_kernel import (
        decode_updates_v1,
        identity_rank,
        pack_updates,
    )
    from ytpu.ops.integrate_kernel import apply_update_stream_fused

    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text("text")
    with doc.transact() as txn:
        txt.insert(txn, 0, "hello")

    buf_np, lens_np = pack_updates(log)
    decode = jax.jit(partial(decode_updates_v1, max_rows=4, max_dels=8))
    stream, flags = decode(jnp.asarray(buf_np), jnp.asarray(lens_np))
    rank = identity_rank(256)

    def case(n_docs, cap, d_block):
        xla = apply_update_stream(init_state(n_docs, cap), stream, rank)
        fused = apply_update_stream_fused(
            init_state(n_docs, cap), stream, rank,
            d_block=d_block, guard=False, refresh_cache=False,
        )
        bad = {}
        for name in xla.blocks._fields:
            if name == "origin_slot":
                continue
            va = np.asarray(getattr(xla.blocks, name))
            vb = np.asarray(getattr(fused.blocks, name))
            if not np.array_equal(va, vb):
                docs_b, slots_b = np.nonzero(va != vb)
                bad[name] = sorted(set(int(s) for s in slots_b))[:6]
        return bad

    for n_docs, cap, d_block in (
        (8, 512, 8),
        (8, 256, 8),
        (8, 128, 8),
        (8, 1024, 8),
        (8, 512, 4),
        (8, 512, 2),
        (8, 512, 1),
        (16, 512, 16),
        (4, 512, 4),
    ):
        key = f"docs{n_docs}_cap{cap}_db{d_block}"
        t0 = time.time()
        try:
            bad = case(n_docs, cap, d_block)
            state["cases"][key] = {
                "divergent": bad or None,
                "seconds": round(time.time() - t0, 1),
            }
        except Exception as e:  # noqa: BLE001
            state["cases"][key] = {"error": f"{type(e).__name__}: {e}"[:200]}
        flush()
    print(json.dumps(state))
    return 0


if __name__ == "__main__":
    sys.exit(main())
