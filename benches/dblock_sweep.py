"""Sweep fused-kernel tile size (d_block) on the real chip.

Usage: python benches/dblock_sweep.py [--docs 4096] [--updates 600]
Prints one line per d_block: rate + speedup over the first entry.
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")

import numpy as np

import bench as B


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=B.N_DOCS)
    ap.add_argument("--updates", type=int, default=B.N_UPDATES)
    ap.add_argument("--blocks", type=int, nargs="*", default=[8, 16, 32, 64])
    ap.add_argument("--capacity", type=int, default=B.CAPACITY)
    args = ap.parse_args()

    import os

    if os.path.exists(B.TRACE_PATH):
        ops = B.load_b4_ops(args.updates)
    else:
        ops = B.synthetic_ops(args.updates)
    log, expect = B.build_updates(ops)

    from ytpu.core import Update
    from ytpu.models.batch_doc import BatchEncoder, get_string, init_state
    from ytpu.ops.integrate_kernel import apply_update_stream_fused

    enc = BatchEncoder()
    steps = [
        enc.build_step(Update.decode_v1(p), B.ROWS_PER_STEP, B.DELS_PER_STEP)
        for p in log
    ]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()

    base = None
    for db in args.blocks:
        if args.docs % db:
            continue
        # compile + correctness
        state = init_state(args.docs, args.capacity)
        state = apply_update_stream_fused(
            state, stream, rank, d_block=db, guard=False, refresh_cache=False
        )
        assert int(np.asarray(state.error).max()) == 0
        assert get_string(state, 0, enc.payloads) == expect
        # timed
        best = float("inf")
        for _ in range(2):
            state = init_state(args.docs, args.capacity)
            np.asarray(state.n_blocks)
            t0 = time.perf_counter()
            state = apply_update_stream_fused(
                state, stream, rank, d_block=db, guard=False,
                refresh_cache=False,  # keep the cache rebuild out of the sweep
            )
            np.asarray(state.n_blocks)
            best = min(best, time.perf_counter() - t0)
        rate = len(log) * args.docs / best
        if base is None:
            base = rate
        print(
            f"d_block={db:4d}  {best*1e3:8.1f} ms  {rate/1e6:8.2f} M updates/s"
            f"  x{rate/base:.2f}"
        )


if __name__ == "__main__":
    main()
