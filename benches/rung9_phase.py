"""Phase-level bisection of the fused kernel's silicon divergence.

Oracle: the SAME truncated kernel (``_debug_phases`` / ``_debug_row_phase``)
run in interpret mode vs on hardware — any diff is a Mosaic miscompile of
whatever the truncation includes. Each case runs in a SUBPROCESS so a TPU
worker crash cannot poison later cases (the in-process jax client never
reconnects after UNAVAILABLE).

Usage:
  python benches/rung9_phase.py            # sweep
  python benches/rung9_phase.py one P RP   # single case (child mode)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

OUT = os.path.join(HERE, "benches", "rung9_phase.json")


def one_case(phases: int, row_phase: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial

    from ytpu.core import Doc
    from ytpu.models.batch_doc import init_state
    from ytpu.ops.decode_kernel import (
        decode_updates_v1,
        identity_rank,
        pack_updates,
    )
    from ytpu.ops.integrate_kernel import M_PAD, _run, pack_state, pack_stream

    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text("text")
    with doc.transact() as txn:
        txt.insert(txn, 0, "hello")

    buf_np, lens_np = pack_updates(log)
    decode = jax.jit(partial(decode_updates_v1, max_rows=4, max_dels=8))
    stream, flags = decode(jnp.asarray(buf_np), jnp.asarray(lens_np))
    rank = identity_rank(256)
    rows, dels = pack_stream(stream)

    def run(interpret):
        cols, meta = pack_state(init_state(8, 512))
        return _run(
            cols, meta, (rows, dels, rank), 8, interpret, phases, row_phase
        )

    ci, mi = run(True)
    ci, mi = np.asarray(ci), np.asarray(mi)
    ch, mh = run(False)
    ch, mh = np.asarray(ch), np.asarray(mh)
    bad = np.nonzero(ci != ch)
    meta_bad = np.nonzero(mi != mh)
    out = {
        "phases": phases,
        "row_phase": row_phase,
        "n_bad_cols": int(bad[0].size),
        "n_bad_meta": int(meta_bad[0].size),
    }
    if bad[0].size:
        # first few divergent (plane, doc, slot, interp, hw)
        out["first_bad"] = [
            [
                int(bad[0][k]),
                int(bad[1][k]),
                int(bad[2][k]),
                int(ci[bad[0][k], bad[1][k], bad[2][k]]),
                int(ch[bad[0][k], bad[1][k], bad[2][k]]),
            ]
            for k in range(min(6, bad[0].size))
        ]
    return out


def main() -> int:
    if len(sys.argv) == 4 and sys.argv[1] == "one":
        print(json.dumps(one_case(int(sys.argv[2]), int(sys.argv[3]))))
        return 0

    state: dict = {"cases": {}}

    def flush():
        with open(OUT, "w") as f:
            json.dump(state, f, indent=1)

    for phases, row_phase in (
        (1, 1),
        (1, 2),
        (1, 3),
        (1, 4),
        (2, 4),
        (3, 4),
    ):
        key = f"p{phases}_rp{row_phase}"
        t0 = time.time()
        try:
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "one",
                 str(phases), str(row_phase)],
                capture_output=True, text=True, timeout=420, cwd=HERE,
            )
            line = res.stdout.strip().splitlines()[-1] if res.stdout.strip() else ""
            state["cases"][key] = (
                json.loads(line)
                if line.startswith("{")
                else {"error": (res.stderr or res.stdout)[-250:]}
            )
        except Exception as e:  # noqa: BLE001
            state["cases"][key] = {"error": f"{type(e).__name__}: {e}"[:200]}
        state["cases"][key]["seconds"] = round(time.time() - t0, 1)
        flush()
    print(json.dumps(state))
    return 0


if __name__ == "__main__":
    sys.exit(main())
