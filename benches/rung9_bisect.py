"""Bisect the fused kernel's rung-9 hardware divergence.

mosaic_ladder rung 9 (200-op text replay through the fused kernel on
silicon) died in the move-aware walk with a cycle, while rung 8 (1 op)
and rung 10 (moves, 6 ops) pass, and interpret-mode parity is green in
CI — a silicon-only divergence. This driver:

  1. replays N ops through BOTH lanes on hardware (fused vs un-fused
     XLA) for growing N until they diverge;
  2. at the first failing N, reports the first divergent doc/slot/column
     so the miscompiled construct can be attributed.

Usage: python benches/rung9_bisect.py
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

OUT = os.path.join(HERE, "benches", "rung9_bisect.json")
state: dict = {"steps": {}}


def flush():
    with open(OUT, "w") as f:
        json.dump(state, f, indent=1)


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    state["platform"] = jax.devices()[0].platform
    flush()

    from ytpu.core import Doc
    from ytpu.models.batch_doc import apply_update_stream, init_state
    from ytpu.ops.decode_kernel import (
        decode_updates_v1,
        identity_rank,
        pack_updates,
    )
    from ytpu.ops.integrate_kernel import apply_update_stream_fused
    from functools import partial

    def replay_log(n_ops):
        doc = Doc(client_id=1)
        log = []
        doc.observe_update_v1(lambda p, o, t: log.append(p))
        txt = doc.get_text("text")
        for i in range(n_ops):
            with doc.transact() as txn:
                txt.insert(txn, i % max(1, min(i, 40)), f"w{i % 7}")
        return log, txt.get_string()

    rank = identity_rank(256)

    def run_n(n_ops, n_docs=8, cap=512):
        log, expect = replay_log(n_ops)
        buf_np, lens_np = pack_updates(log)
        decode = jax.jit(partial(decode_updates_v1, max_rows=4, max_dels=8))
        stream, flags = decode(jnp.asarray(buf_np), jnp.asarray(lens_np))
        xla = apply_update_stream(init_state(n_docs, cap), stream, rank)
        fused = apply_update_stream_fused(
            init_state(n_docs, cap), stream, rank,
            d_block=min(8, n_docs), guard=False, refresh_cache=False,
        )
        err_x = int(np.asarray(xla.error).max())
        err_f = int(np.asarray(fused.error).max())
        divergent = []
        for name in xla.blocks._fields:
            if name == "origin_slot":
                continue  # fused lane leaves the cache plane stale by design
            va = np.asarray(getattr(xla.blocks, name))
            vb = np.asarray(getattr(fused.blocks, name))
            if not np.array_equal(va, vb):
                d, s = [int(x[0]) for x in np.nonzero(va != vb)[:2]]
                divergent.append(
                    {
                        "col": name,
                        "doc": d,
                        "slot": s,
                        "xla": int(va[d, s]),
                        "fused": int(vb[d, s]),
                    }
                )
        same_meta = {
            "start": bool(np.array_equal(np.asarray(xla.start), np.asarray(fused.start))),
            "n_blocks": bool(
                np.array_equal(np.asarray(xla.n_blocks), np.asarray(fused.n_blocks))
            ),
        }
        return {
            "n_ops": n_ops,
            "err_xla": err_x,
            "err_fused": err_f,
            "divergent_cols": divergent[:8],
            "meta_equal": same_meta,
        }

    for n in (1, 25, 50, 100, 150, 200):
        t0 = time.time()
        try:
            r = run_n(n)
        except Exception as e:  # noqa: BLE001
            r = {"n_ops": n, "error": f"{type(e).__name__}: {e}"[:300]}
        r["seconds"] = round(time.time() - t0, 1)
        state["steps"][str(n)] = r
        flush()
        if r.get("divergent_cols") or r.get("error"):
            state["first_divergence"] = n
            flush()
            break
    print(json.dumps(state))
    return 0


if __name__ == "__main__":
    sys.exit(main())
