"""Repro 3: layer in the _run context pieces until the corruption fires.

At _debug_row_phase=1 the kernel writes meta only — cols_ref is NEVER
written — yet the aliased cols output returns with zeroed tail lane
groups on hardware. Candidate triggers vs the clean micro:

  v_vmem  : + CompilerParams(vmem_limit_bytes=64MB)
  v_multi : + 5 inputs / 2 outputs with {3:0, 4:1} aliasing (_run shape)
  v_body  : + S*U fori + pl.when + a [DB,C] masked-max reduce + meta RMW
  v_full  : all of the above (minus any cols_ref write)

Usage: python benches/plane_rmw_repro3.py
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

OUT = os.path.join(HERE, "benches", "plane_rmw_repro3.json")
state: dict = {"cases": {}}


def flush():
    with open(OUT, "w") as f:
        json.dump(state, f, indent=1)


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    state["platform"] = jax.devices()[0].platform
    flush()

    I32 = jnp.int32
    NC, D, C, DB = 26, 8, 512, 8
    S, U, W = 1, 4, 23
    M_PAD = 8
    x3 = (np.arange(NC * D * C, dtype=np.int32).reshape(NC, D, C) % 997) - 400
    rows_np = np.arange(S * U * W, dtype=np.int32).reshape(S, U, W) % 7
    rows_np[:, :, 14] = 1  # valid flag
    dels_np = np.zeros((S, 4, 4), np.int32)
    rank_np = np.arange(256, dtype=np.int32).reshape(1, 256)
    meta_np = np.zeros((D, M_PAD), np.int32)

    def record(name, fn):
        state["cases"][name] = {"status": "running"}
        flush()
        t0 = time.time()
        try:
            n_bad, first = fn()
            state["cases"][name] = {
                "status": "ok" if n_bad == 0 else "CORRUPT",
                "n_bad": n_bad,
                "first_bad": first,
            }
        except Exception as e:  # noqa: BLE001
            state["cases"][name] = {
                "status": "fail", "error": f"{type(e).__name__}: {e}"[:250],
            }
        state["cases"][name]["seconds"] = round(time.time() - t0, 1)
        flush()

    def diff3(got):
        bad = np.nonzero(got != x3)
        first = (
            [[int(bad[j][k]) for j in range(3)]
             + [int(x3[bad[0][k], bad[1][k], bad[2][k]]),
                int(got[bad[0][k], bad[1][k], bad[2][k]])]
             for k in range(min(4, bad[0].size))]
            if bad[0].size else None
        )
        return int(bad[0].size), first

    def passthrough_k(x_ref, o_ref):
        for i in range(NC):
            o_ref[i] = x_ref[i]

    def v_vmem():
        out = pl.pallas_call(
            passthrough_k,
            grid=(D // DB,),
            in_specs=[pl.BlockSpec((NC, DB, C), lambda d: (0, d, 0))],
            out_specs=pl.BlockSpec((NC, DB, C), lambda d: (0, d, 0)),
            out_shape=jax.ShapeDtypeStruct((NC, D, C), I32),
            input_output_aliases={0: 0},
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=64 * 1024 * 1024
            ),
        )(jnp.asarray(x3))
        return diff3(np.asarray(out))

    record("v_vmem", v_vmem)

    def multi_call(body, name):
        def k(rows_ref, dels_ref, rank_ref, x_ref, meta_ref, o_ref, mo_ref):
            body(rows_ref, dels_ref, rank_ref, x_ref, meta_ref, mo_ref)
            # NOTE: cols output (o_ref) is intentionally NEVER written —
            # with aliasing {3:0} it must come back as the input

        def run():
            out, mo = pl.pallas_call(
                k,
                grid=(D // DB,),
                in_specs=[
                    pl.BlockSpec(rows_np.shape, lambda d: (0, 0, 0)),
                    pl.BlockSpec(dels_np.shape, lambda d: (0, 0, 0)),
                    pl.BlockSpec(rank_np.shape, lambda d: (0, 0)),
                    pl.BlockSpec((NC, DB, C), lambda d: (0, d, 0)),
                    pl.BlockSpec((DB, M_PAD), lambda d: (d, 0)),
                ],
                out_specs=[
                    pl.BlockSpec((NC, DB, C), lambda d: (0, d, 0)),
                    pl.BlockSpec((DB, M_PAD), lambda d: (d, 0)),
                ],
                out_shape=[
                    jax.ShapeDtypeStruct((NC, D, C), I32),
                    jax.ShapeDtypeStruct((D, M_PAD), I32),
                ],
                input_output_aliases={3: 0, 4: 1},
                compiler_params=pltpu.CompilerParams(
                    vmem_limit_bytes=64 * 1024 * 1024
                ),
            )(
                jnp.asarray(rows_np),
                jnp.asarray(dels_np),
                jnp.asarray(rank_np),
                jnp.asarray(x3),
                jnp.asarray(meta_np),
            )
            return diff3(np.asarray(out))

        record(name, run)

    def body_noop(rows_ref, dels_ref, rank_ref, x_ref, meta_ref, mo_ref):
        mo_ref[:, :] = meta_ref[:, :]

    multi_call(body_noop, "v_multi")

    def body_full(rows_ref, dels_ref, rank_ref, x_ref, meta_ref, mo_ref):
        mo_ref[:, :] = meta_ref[:, :]
        iota_c = jax.lax.broadcasted_iota(I32, (DB, C), 1)

        def client_clock(client_v):
            m = (iota_c < mo_ref[:, 1][:, None]) & (
                x_ref[0] == client_v[:, None]
            )
            return jnp.max(jnp.where(m, x_ref[1] + x_ref[2], 0), axis=1)

        def step(s, _):
            def row_body(u, __):
                @pl.when(rows_ref[s, u, 14] == 1)
                def _():
                    local = client_clock(rows_ref[s, u, 0])
                    missing = ~(local >= rows_ref[s, u, 1])
                    mo_ref[:, 2] = mo_ref[:, 2] | jnp.where(missing, 2, 0)

                return 0

            jax.lax.fori_loop(0, U, row_body, 0)
            return 0

        jax.lax.fori_loop(0, S, step, 0)

    multi_call(body_full, "v_body")

    print(json.dumps(state))
    return 0


if __name__ == "__main__":
    sys.exit(main())
