"""Bisect the TPU-worker crash in the flagship xla-lane full replay.

`FusedReplay(lane="xla")` kills the TPU worker process (observed twice on
fresh workers, 2026-08-01). Per-chunk it runs exactly two device
programs: the chunked device decode (`decode_updates_v1`, n_steps=chunk)
and the un-fused integrate scan (`_xla_chunk_step`: unpack →
apply_update_stream's lax.scan → repack). This driver runs each in
isolation at increasing shapes, flushing a JSON line per stage, so the
worker crash attributes to a named stage + shape.

Usage: python benches/flagship_bisect.py [out.json]
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

OUT = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
    HERE, "benches", "flagship_bisect.json"
)
state: dict = {"stages": {}}


def flush():
    with open(OUT, "w") as f:
        json.dump(state, f, indent=1)


def stage(name, fn):
    state["stages"][name] = {"status": "running"}
    flush()
    t0 = time.time()
    try:
        extra = fn() or {}
        state["stages"][name] = {
            "status": "ok", "seconds": round(time.time() - t0, 1), **extra
        }
    except Exception as e:  # noqa: BLE001 — attribute and continue
        state["stages"][name] = {
            "status": "fail",
            "seconds": round(time.time() - t0, 1),
            "error": f"{type(e).__name__}: {e}"[:400],
        }
    flush()
    return state["stages"][name]["status"] == "ok"


def main() -> int:
    spec = importlib.util.spec_from_file_location(
        "ytpu_bench_main", os.path.join(HERE, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    log, _, trace = bench.load_full_log()
    state["trace"] = trace

    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial

    state["platform"] = jax.devices()[0].platform
    flush()

    from ytpu.models.replay import plan_replay, _xla_chunk_step
    from ytpu.ops.decode_kernel import (
        decode_updates_v1,
        identity_rank,
        pack_updates,
    )
    from ytpu.ops.integrate_kernel import pack_state
    from ytpu.models.batch_doc import init_state

    plan = plan_replay(log)
    rank = identity_rank(256)

    def make_chunk(n, chunk):
        batch = log[:n]
        if len(batch) < chunk:
            batch = batch + [b"\x00\x00"] * (chunk - len(batch))
        buf, lens = pack_updates(batch, pad_to=plan.max_len + 16)
        return jnp.asarray(buf), jnp.asarray(lens)

    def run_decode(chunk):
        decode = jax.jit(
            partial(
                decode_updates_v1,
                max_rows=plan.max_rows,
                max_dels=plan.max_dels,
                n_steps=chunk,
                max_sections=plan.max_sections,
            )
        )
        buf, lens = make_chunk(chunk, chunk)
        stream, flags = decode(buf, lens)
        jax.block_until_ready(flags)
        return {"chunk": chunk}

    def run_integrate(chunk, docs, cap):
        decode = jax.jit(
            partial(
                decode_updates_v1,
                max_rows=plan.max_rows,
                max_dels=plan.max_dels,
                n_steps=chunk,
                max_sections=plan.max_sections,
            )
        )
        buf, lens = make_chunk(chunk, chunk)
        stream, flags = decode(buf, lens)
        cols, meta = pack_state(init_state(docs, cap))
        cols, meta = _xla_chunk_step(cols, meta, stream, rank)
        jax.block_until_ready(meta)
        err = int(np.asarray(meta)[:, 2].max())
        return {"chunk": chunk, "docs": docs, "cap": cap, "err": err}

    # crash order: smallest first so the log attributes the first killer
    if not stage("d1_decode_512", lambda: run_decode(512)):
        return 1
    if not stage("d2_decode_8192", lambda: run_decode(8192)):
        return 1
    if not stage("i1_int_512x64x4096", lambda: run_integrate(512, 64, 4096)):
        return 1
    if not stage("i2_int_8192x64x8192", lambda: run_integrate(8192, 64, 8192)):
        return 1
    if not stage(
        "i3_int_8192x1024x8192", lambda: run_integrate(8192, 1024, 8192)
    ):
        return 1
    state["conclusion"] = "all stages passed in isolation"
    flush()
    print(json.dumps(state))
    return 0


if __name__ == "__main__":
    sys.exit(main())
