"""ytpu micro-benchmark suite mirroring the reference's criterion benches.

Workload generators follow /root/reference/yrs/benches/benches.rs:
- B1.1–B1.7: text ops, N=6000 (append/insert/prepend/random/words/ins+del)
- B1.8–B1.11: array ops, N=6000
- B2.1–B2.4: two-doc concurrent editing with per-op update exchange
- B3.1–B3.4: 20*sqrt(N) clients, one txn each, applied into one doc
- B4.1: real-world editing-trace replay (prefix)

Run: `python benches/micro.py [--n 6000] [--json]`
Reports host-oracle wall times (single doc, single core) — the apples-to-
apples shape of the reference suite — plus the batched device replay for
the B4 workload (the ytpu headline path lives in ../bench.py).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import string
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _env import repin_jax_platforms  # noqa: E402

repin_jax_platforms()

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ytpu.core import Doc  # noqa: E402


def timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def gen_string(rng, n):
    return "".join(rng.choice(string.ascii_letters) for _ in range(n))


# --- B1: single-doc text/array ------------------------------------------------


def b1_1_append(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        for i in range(n):
            t.insert(txn, i, "a")


def b1_2_insert_string(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    s = gen_string(rng, n)
    with doc.transact() as txn:
        t.insert(txn, 0, s)


def b1_3_prepend(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        for _ in range(n):
            t.insert(txn, 0, "a")


def b1_4_random_insert(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        size = 0
        for _ in range(n):
            t.insert(txn, rng.randint(0, size), "a")
            size += 1


def b1_5_random_words(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        size = 0
        for _ in range(n):
            w = gen_string(rng, rng.randint(2, 8))
            t.insert(txn, rng.randint(0, size), w)
            size += len(w)


def b1_7_insert_delete(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        size = 0
        for _ in range(n):
            if size > 10 and rng.random() < 0.4:
                pos = rng.randint(0, size - 3)
                k = rng.randint(1, 3)
                t.remove_range(txn, pos, k)
                size -= k
            else:
                w = gen_string(rng, rng.randint(2, 6))
                t.insert(txn, rng.randint(0, size), w)
                size += len(w)


def b1_8_array_append(n, rng):
    doc = Doc(client_id=1)
    a = doc.get_array("array")
    with doc.transact() as txn:
        for i in range(n):
            a.insert(txn, i, i)


def b1_9_array_insert_batch(n, rng):
    doc = Doc(client_id=1)
    a = doc.get_array("array")
    with doc.transact() as txn:
        a.insert_range(txn, 0, list(range(n)))


def b1_10_array_prepend(n, rng):
    doc = Doc(client_id=1)
    a = doc.get_array("array")
    with doc.transact() as txn:
        for _ in range(n):
            a.insert(txn, 0, 0)


def b1_11_array_random(n, rng):
    doc = Doc(client_id=1)
    a = doc.get_array("array")
    with doc.transact() as txn:
        size = 0
        for i in range(n):
            a.insert(txn, rng.randint(0, size), i)
            size += 1


# --- B2: two docs, concurrent, per-op exchange --------------------------------


def b2_concurrent(n, rng):
    """B2.2-shaped: both peers insert at random positions, per-op exchange."""
    a, b = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a.get_text("text"), b.get_text("text")
    la, lb = [], []
    a.observe_update_v1(lambda p, o, t: la.append(p))
    b.observe_update_v1(lambda p, o, t: lb.append(p))
    for _ in range(n):
        with a.transact() as txn:
            ta.insert(txn, rng.randint(0, len(ta)), "a")
        ua = la[-1]  # capture before remote applies append echo events
        with b.transact() as txn:
            tb.insert(txn, rng.randint(0, len(tb)), "b")
        ub = lb[-1]
        b.apply_update_v1(ua)
        a.apply_update_v1(ub)
    assert ta.get_string() == tb.get_string()


# --- B3: many clients fan-in --------------------------------------------------


def b3_fanin_map(n, rng):
    n_clients = int(20 * math.sqrt(n))
    updates = []
    for i in range(n_clients):
        peer = Doc(client_id=i + 1)
        m = peer.get_map("map")
        with peer.transact() as txn:
            m.insert(txn, f"key-{i}", i)
        updates.append(peer.encode_state_as_update_v1())
    target = Doc(client_id=0xFFFF)
    for u in updates:
        target.apply_update_v1(u)
    assert len(target.get_map("map").to_json()) == n_clients


def b3_fanin_array(n, rng):
    n_clients = int(20 * math.sqrt(n))
    updates = []
    for i in range(n_clients):
        peer = Doc(client_id=i + 1)
        a = peer.get_array("array")
        with peer.transact() as txn:
            a.push_back(txn, i)
        updates.append(peer.encode_state_as_update_v1())
    target = Doc(client_id=0xFFFF)
    for u in updates:
        target.apply_update_v1(u)
    assert len(target.get_array("array")) == n_clients


# --- device lanes (VERDICT r2 weak #9: B1-B3 had host-oracle times only) ---


def _stream_logs(gen_ops):
    """Per-op wire updates from a host generator (one txn per op)."""
    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    gen_ops(doc)
    return log


def device_b1_text(n, rng, d_docs=512):
    """B1-shaped text op stream (random inserts + deletes, one update per
    op) integrated over a d_docs batch on the raw-bytes device lane."""
    from ytpu.models.ingest import BatchIngestor

    def ops(doc):
        t = doc.get_text("text")
        for _ in range(n):
            with doc.transact() as txn:
                ln = len(t)
                if ln > 10 and rng.random() < 0.3:
                    t.remove_range(txn, rng.randint(0, ln - 2), 1)
                else:
                    t.insert(txn, rng.randint(0, ln), rng.choice(string.ascii_letters))

    log = _stream_logs(ops)
    ing = BatchIngestor(d_docs, 4096)
    # warmup compile on the first update, then time the stream
    ing.apply_bytes([log[0]] * d_docs)
    t0 = time.perf_counter()
    for p in log[1:]:
        ing.apply_bytes([p] * d_docs)
    dt = time.perf_counter() - t0
    assert ing.fast_docs > 0
    return {
        "updates_per_sec": round((len(log) - 1) * d_docs / dt, 1),
        "docs": d_docs,
        "n_updates": len(log) - 1,
        "fast_docs": ing.fast_docs,
    }


def device_b2_concurrent(n, rng, d_docs=512):
    """B2-shaped: the two peers' interleaved update stream (per-op
    exchange order) integrated over a d_docs batch."""
    from ytpu.models.ingest import BatchIngestor

    a, b = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a.get_text("text"), b.get_text("text")
    la, lb = [], []
    a.observe_update_v1(lambda p, o, t: la.append(p))
    b.observe_update_v1(lambda p, o, t: lb.append(p))
    stream = []
    for _ in range(n):
        with a.transact() as txn:
            ta.insert(txn, rng.randint(0, len(ta)), "a")
        ua = la[-1]
        with b.transact() as txn:
            tb.insert(txn, rng.randint(0, len(tb)), "b")
        ub = lb[-1]
        b.apply_update_v1(ua)
        a.apply_update_v1(ub)
        stream.extend((ua, ub))
    ing = BatchIngestor(d_docs, 4096)
    ing.apply_bytes([stream[0]] * d_docs)
    t0 = time.perf_counter()
    for p in stream[1:]:
        ing.apply_bytes([p] * d_docs)
    dt = time.perf_counter() - t0
    assert ing.fast_docs > 0, "stream never took the device lane"
    return {
        "updates_per_sec": round((len(stream) - 1) * d_docs / dt, 1),
        "docs": d_docs,
        "n_updates": len(stream) - 1,
        "fast_docs": ing.fast_docs,
        "slow_docs": ing.slow_docs,
    }


def device_b3_fanin(n, rng, d_docs=512):
    """B3-shaped: 20*sqrt(N) one-txn clients fanned into every doc slot
    of the batch (map keys -> per-key LWW chains on device)."""
    from ytpu.models.ingest import BatchIngestor

    n_clients = int(20 * math.sqrt(n))
    updates = []
    for i in range(n_clients):
        peer = Doc(client_id=i + 1)
        m = peer.get_map("map")
        with peer.transact() as txn:
            m.insert(txn, f"key-{i}", i)
        updates.append(peer.encode_state_as_update_v1())
    ing = BatchIngestor(d_docs, max(4096, 2 * n_clients))
    ing.apply_bytes([updates[0]] * d_docs)
    t0 = time.perf_counter()
    for p in updates[1:]:
        ing.apply_bytes([p] * d_docs)
    dt = time.perf_counter() - t0
    assert ing.fast_docs > 0, "fan-in never took the device lane"
    return {
        "updates_per_sec": round((len(updates) - 1) * d_docs / dt, 1),
        "docs": d_docs,
        "n_clients": n_clients,
        "fast_docs": ing.fast_docs,
        "slow_docs": ing.slow_docs,
    }


DEVICE_BENCHES = [
    ("B1.dev text op stream", device_b1_text),
    ("B2.dev concurrent exchange stream", device_b2_concurrent),
    ("B3.dev many-client fan-in", device_b3_fanin),
]


BENCHES = [
    ("B1.1 append N chars", b1_1_append),
    ("B1.2 insert string len N", b1_2_insert_string),
    ("B1.3 prepend N chars", b1_3_prepend),
    ("B1.4 random char inserts", b1_4_random_insert),
    ("B1.5 random word inserts", b1_5_random_words),
    ("B1.7 random insert/delete", b1_7_insert_delete),
    ("B1.8 array append", b1_8_array_append),
    ("B1.9 array insert batch", b1_9_array_insert_batch),
    ("B1.10 array prepend", b1_10_array_prepend),
    ("B1.11 array random insert", b1_11_array_random),
    ("B2.2 two docs concurrent + exchange", b2_concurrent),
    ("B3.1 20*sqrt(N) clients map fan-in", b3_fanin_map),
    ("B3.4 20*sqrt(N) clients array fan-in", b3_fanin_array),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=6000)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--only", type=str, default=None)
    ap.add_argument("--device", action="store_true",
                    help="also run the B1-B3 device lanes (batched engine)")
    ap.add_argument("--device-docs", type=int, default=512)
    args = ap.parse_args()

    results = {}
    for name, fn in BENCHES:
        if args.only and args.only not in name:
            continue
        n = args.n
        if name.startswith("B2"):
            n = min(n, 1000)  # per-op exchange is O(n^2)-ish on the oracle
        rng = random.Random(42)
        dt = timed(lambda: fn(n, rng))
        results[name] = round(dt * 1000, 1)
        if not args.json:
            print(f"{name:44s} {dt * 1000:9.1f} ms  (N={n})")
    if args.device:
        for name, fn in DEVICE_BENCHES:
            if args.only and args.only not in name:
                continue
            n = min(args.n, 600)  # per-update dispatch: keep the loop sane
            rng = random.Random(42)
            out = fn(n, rng, d_docs=args.device_docs)
            results[name] = out
            if not args.json:
                print(f"{name:44s} {out['updates_per_sec']:12,.0f} updates/s "
                      f"({out['docs']}-doc batch)")
    if args.json:
        print(json.dumps(results))


if __name__ == "__main__":
    main()
