"""ytpu micro-benchmark suite mirroring the reference's criterion benches.

Workload generators follow /root/reference/yrs/benches/benches.rs:
- B1.1–B1.7: text ops, N=6000 (append/insert/prepend/random/words/ins+del)
- B1.8–B1.11: array ops, N=6000
- B2.1–B2.4: two-doc concurrent editing with per-op update exchange
- B3.1–B3.4: 20*sqrt(N) clients, one txn each, applied into one doc
- B4.1: real-world editing-trace replay (prefix)

Run: `python benches/micro.py [--n 6000] [--json]`
Reports host-oracle wall times (single doc, single core) — the apples-to-
apples shape of the reference suite — plus the batched device replay for
the B4 workload (the ytpu headline path lives in ../bench.py).
"""

from __future__ import annotations

import argparse
import json
import math
import random
import string
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ytpu.core import Doc  # noqa: E402


def timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def gen_string(rng, n):
    return "".join(rng.choice(string.ascii_letters) for _ in range(n))


# --- B1: single-doc text/array ------------------------------------------------


def b1_1_append(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        for i in range(n):
            t.insert(txn, i, "a")


def b1_2_insert_string(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    s = gen_string(rng, n)
    with doc.transact() as txn:
        t.insert(txn, 0, s)


def b1_3_prepend(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        for _ in range(n):
            t.insert(txn, 0, "a")


def b1_4_random_insert(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        size = 0
        for _ in range(n):
            t.insert(txn, rng.randint(0, size), "a")
            size += 1


def b1_5_random_words(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        size = 0
        for _ in range(n):
            w = gen_string(rng, rng.randint(2, 8))
            t.insert(txn, rng.randint(0, size), w)
            size += len(w)


def b1_7_insert_delete(n, rng):
    doc = Doc(client_id=1)
    t = doc.get_text("text")
    with doc.transact() as txn:
        size = 0
        for _ in range(n):
            if size > 10 and rng.random() < 0.4:
                pos = rng.randint(0, size - 3)
                k = rng.randint(1, 3)
                t.remove_range(txn, pos, k)
                size -= k
            else:
                w = gen_string(rng, rng.randint(2, 6))
                t.insert(txn, rng.randint(0, size), w)
                size += len(w)


def b1_8_array_append(n, rng):
    doc = Doc(client_id=1)
    a = doc.get_array("array")
    with doc.transact() as txn:
        for i in range(n):
            a.insert(txn, i, i)


def b1_9_array_insert_batch(n, rng):
    doc = Doc(client_id=1)
    a = doc.get_array("array")
    with doc.transact() as txn:
        a.insert_range(txn, 0, list(range(n)))


def b1_10_array_prepend(n, rng):
    doc = Doc(client_id=1)
    a = doc.get_array("array")
    with doc.transact() as txn:
        for _ in range(n):
            a.insert(txn, 0, 0)


def b1_11_array_random(n, rng):
    doc = Doc(client_id=1)
    a = doc.get_array("array")
    with doc.transact() as txn:
        size = 0
        for i in range(n):
            a.insert(txn, rng.randint(0, size), i)
            size += 1


# --- B2: two docs, concurrent, per-op exchange --------------------------------


def b2_concurrent(n, rng):
    """B2.2-shaped: both peers insert at random positions, per-op exchange."""
    a, b = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a.get_text("text"), b.get_text("text")
    la, lb = [], []
    a.observe_update_v1(lambda p, o, t: la.append(p))
    b.observe_update_v1(lambda p, o, t: lb.append(p))
    for _ in range(n):
        with a.transact() as txn:
            ta.insert(txn, rng.randint(0, len(ta)), "a")
        ua = la[-1]  # capture before remote applies append echo events
        with b.transact() as txn:
            tb.insert(txn, rng.randint(0, len(tb)), "b")
        ub = lb[-1]
        b.apply_update_v1(ua)
        a.apply_update_v1(ub)
    assert ta.get_string() == tb.get_string()


# --- B3: many clients fan-in --------------------------------------------------


def b3_fanin_map(n, rng):
    n_clients = int(20 * math.sqrt(n))
    updates = []
    for i in range(n_clients):
        peer = Doc(client_id=i + 1)
        m = peer.get_map("map")
        with peer.transact() as txn:
            m.insert(txn, f"key-{i}", i)
        updates.append(peer.encode_state_as_update_v1())
    target = Doc(client_id=0xFFFF)
    for u in updates:
        target.apply_update_v1(u)
    assert len(target.get_map("map").to_json()) == n_clients


def b3_fanin_array(n, rng):
    n_clients = int(20 * math.sqrt(n))
    updates = []
    for i in range(n_clients):
        peer = Doc(client_id=i + 1)
        a = peer.get_array("array")
        with peer.transact() as txn:
            a.push_back(txn, i)
        updates.append(peer.encode_state_as_update_v1())
    target = Doc(client_id=0xFFFF)
    for u in updates:
        target.apply_update_v1(u)
    assert len(target.get_array("array")) == n_clients


BENCHES = [
    ("B1.1 append N chars", b1_1_append),
    ("B1.2 insert string len N", b1_2_insert_string),
    ("B1.3 prepend N chars", b1_3_prepend),
    ("B1.4 random char inserts", b1_4_random_insert),
    ("B1.5 random word inserts", b1_5_random_words),
    ("B1.7 random insert/delete", b1_7_insert_delete),
    ("B1.8 array append", b1_8_array_append),
    ("B1.9 array insert batch", b1_9_array_insert_batch),
    ("B1.10 array prepend", b1_10_array_prepend),
    ("B1.11 array random insert", b1_11_array_random),
    ("B2.2 two docs concurrent + exchange", b2_concurrent),
    ("B3.1 20*sqrt(N) clients map fan-in", b3_fanin_map),
    ("B3.4 20*sqrt(N) clients array fan-in", b3_fanin_array),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=6000)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--only", type=str, default=None)
    args = ap.parse_args()

    results = {}
    for name, fn in BENCHES:
        if args.only and args.only not in name:
            continue
        n = args.n
        if name.startswith("B2"):
            n = min(n, 1000)  # per-op exchange is O(n^2)-ish on the oracle
        rng = random.Random(42)
        dt = timed(lambda: fn(n, rng))
        results[name] = round(dt * 1000, 1)
        if not args.json:
            print(f"{name:44s} {dt * 1000:9.1f} ms  (N={n})")
    if args.json:
        print(json.dumps(results))


if __name__ == "__main__":
    main()
