// ThreadSanitizer runner for the native lib0 codec (SURVEY §5.2: the C++
// host layer runs under TSAN in CI). Four threads concurrently decode the
// same v1 update buffer through the ytpu_decode_update_v1 C ABI; the codec
// must be reentrant with no shared mutable state.
//
// Build: g++ -O1 -g -fsanitize=thread -std=c++17 \
//          tests_ffi/tsan_codec.cpp ytpu/native/lib0_codec.cpp -o tsan_codec
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" {
void *ytpu_decode_update_v1(const uint8_t *data, size_t len);
int ytpu_columns_error(void *handle);
size_t ytpu_columns_n_blocks(void *handle);
const int64_t *ytpu_col_client(void *handle);
void ytpu_columns_free(void *handle);
size_t ytpu_decode_var_uints(const uint8_t *data, size_t len, uint64_t *out,
                             size_t max_out);
}

// one-block v1 update: client 3 inserts "hi" into root text "text"
static const uint8_t kUpdate[] = {0x01, 0x01, 0x03, 0x00, 0x04, 0x01, 0x04,
                                  0x74, 0x65, 0x78, 0x74, 0x02, 0x68, 0x69,
                                  0x00};

int main() {
  int failures = 0;
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([&failures]() {
      for (int i = 0; i < 500; ++i) {
        void *cols = ytpu_decode_update_v1(kUpdate, sizeof(kUpdate));
        if (!cols || ytpu_columns_error(cols) != 0 ||
            ytpu_columns_n_blocks(cols) != 1 || ytpu_col_client(cols)[0] != 3) {
          __atomic_fetch_add(&failures, 1, __ATOMIC_SEQ_CST);
        }
        if (cols) ytpu_columns_free(cols);
        uint64_t out[4];
        const uint8_t varints[] = {0x05, 0xac, 0x02};  // 5, 300
        if (ytpu_decode_var_uints(varints, sizeof(varints), out, 4) != 2 ||
            out[0] != 5 || out[1] != 300) {
          __atomic_fetch_add(&failures, 1, __ATOMIC_SEQ_CST);
        }
      }
    });
  }
  for (auto &t : threads) t.join();
  std::printf(failures == 0 ? "TSAN codec OK\n" : "TSAN codec FAILED (%d)\n",
              failures);
  return failures == 0 ? 0 : 1;
}
