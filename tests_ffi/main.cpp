// C ABI conformance tests for libytpu.
//
// Port model: the reference's C FFI suite (/root/reference/tests-ffi/main.cpp,
// 66 doctest cases incl. an exchange_updates helper :21-56). Uses a tiny
// assert harness instead of doctest (not vendored in this environment).
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "ytpu.h"

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    ++g_checks;                                                            \
    if (!(cond)) {                                                         \
      ++g_failures;                                                        \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      const char *err = ytpu_last_error();                                 \
      if (err) std::fprintf(stderr, "  last error: %s\n", err);            \
    }                                                                      \
  } while (0)

#define CHECK_STR(actual_expr, expected)                                  \
  do {                                                                    \
    char *actual__ = (actual_expr);                                       \
    CHECK(actual__ != nullptr && std::strcmp(actual__, (expected)) == 0); \
    if (actual__ && std::strcmp(actual__, (expected)) != 0)               \
      std::fprintf(stderr, "  actual: %s\n", actual__);                   \
    ystring_destroy(actual__);                                            \
  } while (0)

// reference tests-ffi/main.cpp:21-56 — bidirectional state-vector exchange
static void exchange_updates(YDoc *a, YDoc *b) {
  for (int dir = 0; dir < 2; ++dir) {
    YDoc *src = dir == 0 ? a : b;
    YDoc *dst = dir == 0 ? b : a;
    YTransaction *src_txn = ydoc_read_transaction(src);
    YTransaction *dst_txn = ydoc_write_transaction(dst, 0, nullptr);
    YBinary sv = ytransaction_state_vector_v1(dst_txn);
    YBinary diff = ytransaction_state_diff_v1(src_txn, sv.data, (uint32_t)sv.len);
    CHECK(ytransaction_apply(dst_txn, diff.data, (uint32_t)diff.len) == 0);
    ybinary_destroy(sv);
    ybinary_destroy(diff);
    ytransaction_commit(src_txn);
    ytransaction_commit(dst_txn);
  }
}

static void test_doc_lifecycle() {
  YOptions opts{};
  opts.id = 42;
  opts.guid = "doc-guid-1";
  opts.collection_id = "coll";
  opts.encoding = Y_OFFSET_UTF16;
  opts.should_load = 1;
  YDoc *doc = ydoc_new_with_options(opts);
  CHECK(doc != nullptr);
  CHECK(ydoc_id(doc) == 42);
  CHECK_STR(ydoc_guid(doc), "doc-guid-1");
  CHECK_STR(ydoc_collection_id(doc), "coll");
  CHECK(ydoc_should_load(doc) == 1);
  CHECK(ydoc_auto_load(doc) == 0);
  ydoc_destroy(doc);

  YDoc *rnd = ydoc_new();
  CHECK(rnd != nullptr);
  CHECK(ydoc_id(rnd) != 0);
  char *guid = ydoc_guid(rnd);
  CHECK(guid != nullptr && std::strlen(guid) > 0);
  ystring_destroy(guid);
  ydoc_destroy(rnd);
}

static void test_text_basic() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "text");
  CHECK(ytype_kind(txt) == Y_TEXT);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  CHECK(ytransaction_writeable(txn) == 1);
  ytext_insert(txt, txn, 0, "hello!", nullptr);
  ytext_insert(txt, txn, 5, " world", nullptr);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  CHECK_STR(ytext_string(txt, txn), "hello world!");
  CHECK(ytext_len(txt, txn) == 12);
  ytext_remove_range(txt, txn, 5, 6);
  CHECK_STR(ytext_string(txt, txn), "hello!");
  ytransaction_commit(txn);

  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

static void test_text_exchange() {
  YDoc *a = ydoc_new();
  YDoc *b = ydoc_new();
  Branch *ta = ytext(a, "t");
  Branch *tb = ytext(b, "t");

  YTransaction *txn = ydoc_write_transaction(a, 0, nullptr);
  ytext_insert(ta, txn, 0, "abc", nullptr);
  ytransaction_commit(txn);
  txn = ydoc_write_transaction(b, 0, nullptr);
  ytext_insert(tb, txn, 0, "XYZ", nullptr);
  ytransaction_commit(txn);

  exchange_updates(a, b);

  txn = ydoc_read_transaction(a);
  char *sa = ytext_string(ta, txn);
  ytransaction_commit(txn);
  txn = ydoc_read_transaction(b);
  char *sb = ytext_string(tb, txn);
  ytransaction_commit(txn);
  CHECK(sa != nullptr && sb != nullptr && std::strcmp(sa, sb) == 0);
  CHECK(sa != nullptr && std::strlen(sa) == 6);
  ystring_destroy(sa);
  ystring_destroy(sb);

  ybranch_destroy(ta);
  ybranch_destroy(tb);
  ydoc_destroy(a);
  ydoc_destroy(b);
}

static void test_map() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "map");
  CHECK(ytype_kind(map) == Y_MAP);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput num{};
  num.tag = Y_JSON_NUM;
  num.value.num = 3.5;
  ymap_insert(map, txn, "pi", &num);
  YInput str{};
  str.tag = Y_JSON_STR;
  str.value.str = "value";
  ymap_insert(map, txn, "key", &str);
  YInput arr{};
  arr.tag = Y_JSON_ARR;
  arr.value.str = "[1,2,3]";
  ymap_insert(map, txn, "list", &arr);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(doc);
  CHECK(ymap_len(map, txn) == 3);
  YOutput *pi = ymap_get(map, txn, "pi");
  CHECK(pi != nullptr && youtput_tag(pi) == Y_JSON_NUM);
  CHECK(pi != nullptr && youtput_read_float(pi) == 3.5);
  youtput_destroy(pi);
  YOutput *val = ymap_get(map, txn, "key");
  CHECK(val != nullptr && youtput_tag(val) == Y_JSON_STR);
  CHECK_STR(youtput_read_string(val), "value");
  youtput_destroy(val);
  YOutput *lst = ymap_get(map, txn, "list");
  CHECK(lst != nullptr && youtput_tag(lst) == Y_JSON_ARR);
  CHECK_STR(youtput_json(lst), "[1, 2, 3]");
  youtput_destroy(lst);
  CHECK(ymap_get(map, txn, "missing") == nullptr);
  ytransaction_commit(txn);

  // iterate
  txn = ydoc_read_transaction(doc);
  YMapIter *iter = ymap_iter(map, txn);
  int seen = 0;
  while (YMapEntry *entry = ymap_iter_next(iter)) {
    CHECK(entry->key != nullptr && entry->value != nullptr);
    ++seen;
    ymap_entry_destroy(entry);
  }
  CHECK(seen == 3);
  ymap_iter_destroy(iter);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  CHECK(ymap_remove(map, txn, "pi") == 1);
  CHECK(ymap_remove(map, txn, "pi") == 0);
  CHECK(ymap_len(map, txn) == 2);
  ymap_remove_all(map, txn);
  CHECK(ymap_len(map, txn) == 0);
  ytransaction_commit(txn);

  ybranch_destroy(map);
  ydoc_destroy(doc);
}

static void test_array() {
  YDoc *doc = ydoc_new();
  Branch *arr = yarray(doc, "array");
  CHECK(ytype_kind(arr) == Y_ARRAY);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput items[3];
  items[0].tag = Y_JSON_INT;
  items[0].value.integer = 10;
  items[1].tag = Y_JSON_STR;
  items[1].value.str = "mid";
  items[2].tag = Y_JSON_BOOL;
  items[2].value.flag = 1;
  yarray_insert_range(arr, txn, 0, items, 3);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(doc);
  CHECK(yarray_len(arr) == 3);
  YOutput *v0 = yarray_get(arr, txn, 0);
  CHECK(v0 != nullptr && youtput_read_long(v0) == 10);
  youtput_destroy(v0);
  YOutput *v1 = yarray_get(arr, txn, 1);
  CHECK_STR(youtput_read_string(v1), "mid");
  youtput_destroy(v1);
  YOutput *v2 = yarray_get(arr, txn, 2);
  CHECK(v2 != nullptr && youtput_tag(v2) == Y_JSON_BOOL);
  CHECK(v2 != nullptr && youtput_read_bool(v2) == 1);
  youtput_destroy(v2);

  YArrayIter *iter = yarray_iter(arr, txn);
  int n = 0;
  while (YOutput *item = yarray_iter_next(iter)) {
    ++n;
    youtput_destroy(item);
  }
  CHECK(n == 3);
  yarray_iter_destroy(iter);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  yarray_move(arr, txn, 0, 3);  // move the 10 to the end
  ytransaction_commit(txn);
  txn = ydoc_read_transaction(doc);
  YOutput *last = yarray_get(arr, txn, 2);
  CHECK(last != nullptr && youtput_read_long(last) == 10);
  youtput_destroy(last);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  yarray_remove_range(arr, txn, 0, 2);
  CHECK(yarray_len(arr) == 1);
  ytransaction_commit(txn);

  ybranch_destroy(arr);
  ydoc_destroy(doc);
}

static void test_nested_types() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "root");

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput nested_text{};
  nested_text.tag = Y_TEXT;
  nested_text.value.str = "inner";
  ymap_insert(map, txn, "text", &nested_text);
  YInput nested_arr{};
  nested_arr.tag = Y_ARRAY;
  nested_arr.value.str = "[1,2]";
  ymap_insert(map, txn, "arr", &nested_arr);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  YOutput *out = ymap_get(map, txn, "text");
  CHECK(out != nullptr && youtput_tag(out) == Y_TEXT);
  Branch *inner = youtput_read_ytext(out);
  CHECK(inner != nullptr);
  ytext_insert(inner, txn, 5, "!", nullptr);
  CHECK_STR(ytext_string(inner, txn), "inner!");
  ybranch_destroy(inner);
  youtput_destroy(out);

  YOutput *arr_out = ymap_get(map, txn, "arr");
  CHECK(arr_out != nullptr && youtput_tag(arr_out) == Y_ARRAY);
  Branch *inner_arr = youtput_read_yarray(arr_out);
  CHECK(inner_arr != nullptr && yarray_len(inner_arr) == 2);
  ybranch_destroy(inner_arr);
  youtput_destroy(arr_out);
  ytransaction_commit(txn);

  ybranch_destroy(map);
  ydoc_destroy(doc);
}

static void test_xml() {
  YDoc *doc = ydoc_new();
  Branch *frag = yxmlfragment(doc, "xml");
  CHECK(ytype_kind(frag) == Y_XML_FRAG);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  Branch *div = yxmlelem_insert_elem(frag, txn, 0, "div");
  CHECK(div != nullptr);
  CHECK_STR(yxmlelem_tag(div), "div");
  yxmlelem_insert_attr(div, txn, "class", "header");
  CHECK_STR(yxmlelem_get_attr(div, txn, "class"), "header");
  CHECK(yxmlelem_get_attr(div, txn, "id") == nullptr);

  Branch *txt = yxmlelem_insert_text(div, txn, 0);
  CHECK(txt != nullptr);
  yxmltext_insert(txt, txn, 0, "hi", nullptr);
  CHECK(yxmlelem_child_len(div, txn) == 1);
  CHECK_STR(yxmlelem_string(div, txn), "<div class=\"header\">hi</div>");

  Branch *p = yxmlelem_insert_elem(div, txn, 1, "p");
  CHECK(p != nullptr);
  CHECK(yxmlelem_child_len(div, txn) == 2);

  // siblings from the text node
  YOutput *sib = yxml_next_sibling(txt, txn);
  CHECK(sib != nullptr && youtput_tag(sib) == Y_XML_ELEM);
  youtput_destroy(sib);

  // tree walker from the fragment: div, text, p
  YXmlTreeWalker *walker = yxmlelem_tree_walker(frag, txn);
  int visited = 0;
  while (YOutput *node = yxmlelem_tree_walker_next(walker)) {
    ++visited;
    youtput_destroy(node);
  }
  CHECK(visited == 3);
  yxmlelem_tree_walker_destroy(walker);

  yxmlelem_remove_attr(div, txn, "class");
  CHECK(yxmlelem_get_attr(div, txn, "class") == nullptr);
  ytransaction_commit(txn);

  ybranch_destroy(p);
  ybranch_destroy(txt);
  ybranch_destroy(div);
  ybranch_destroy(frag);
  ydoc_destroy(doc);
}

struct UpdateCollector {
  std::vector<std::vector<uint8_t>> updates;
};

static void collect_update(void *state, uint32_t len, const uint8_t *bytes) {
  auto *collector = (UpdateCollector *)state;
  collector->updates.emplace_back(bytes, bytes + len);
}

static void test_observers() {
  YDoc *a = ydoc_new();
  YDoc *b = ydoc_new();
  Branch *ta = ytext(a, "t");
  Branch *tb = ytext(b, "t");

  UpdateCollector collected;
  YSubscription *sub = ydoc_observe_updates_v1(a, &collected, collect_update);
  CHECK(sub != nullptr);

  YTransaction *txn = ydoc_write_transaction(a, 0, nullptr);
  ytext_insert(ta, txn, 0, "observed", nullptr);
  ytransaction_commit(txn);
  CHECK(collected.updates.size() == 1);

  // live-replicate the captured update into b
  txn = ydoc_write_transaction(b, 0, nullptr);
  CHECK(ytransaction_apply(txn, collected.updates[0].data(),
                           (uint32_t)collected.updates[0].size()) == 0);
  CHECK_STR(ytext_string(tb, txn), "observed");
  ytransaction_commit(txn);

  yunobserve(sub);
  txn = ydoc_write_transaction(a, 0, nullptr);
  ytext_insert(ta, txn, 0, "x", nullptr);
  ytransaction_commit(txn);
  CHECK(collected.updates.size() == 1);  // no longer observing

  ybranch_destroy(ta);
  ybranch_destroy(tb);
  ydoc_destroy(a);
  ydoc_destroy(b);
}

static void test_undo() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YUndoManagerOptions opts{0};
  YUndoManager *mgr = yundo_manager(doc, &opts);
  CHECK(mgr != nullptr);
  yundo_manager_add_scope(mgr, txt);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "hello", nullptr);
  ytransaction_commit(txn);
  txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 5, " world", nullptr);
  ytransaction_commit(txn);

  CHECK(yundo_manager_can_undo(mgr) == 1);
  CHECK(yundo_manager_undo(mgr) == 1);
  txn = ydoc_read_transaction(doc);
  CHECK_STR(ytext_string(txt, txn), "hello");
  ytransaction_commit(txn);

  CHECK(yundo_manager_can_redo(mgr) == 1);
  CHECK(yundo_manager_redo(mgr) == 1);
  txn = ydoc_read_transaction(doc);
  CHECK_STR(ytext_string(txt, txn), "hello world");
  ytransaction_commit(txn);

  yundo_manager_clear(mgr);
  CHECK(yundo_manager_can_undo(mgr) == 0);

  yundo_manager_destroy(mgr);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

static void test_sticky_index() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "hello world", nullptr);

  YStickyIndex *pos = ysticky_index_from_index(txt, txn, 6, Y_ASSOC_AFTER);
  CHECK(pos != nullptr);
  CHECK(ysticky_index_assoc(pos) == Y_ASSOC_AFTER);

  YBinary encoded = ysticky_index_encode(pos);
  CHECK(encoded.data != nullptr && encoded.len > 0);
  YStickyIndex *decoded =
      ysticky_index_decode(encoded.data, (uint32_t)encoded.len);
  CHECK(decoded != nullptr);
  ybinary_destroy(encoded);

  // concurrent insert before the tracked position shifts the index
  ytext_insert(txt, txn, 0, ">> ", nullptr);
  uint32_t index = 0;
  CHECK(ysticky_index_read(pos, txn, &index) == 1);
  CHECK(index == 9);
  CHECK(ysticky_index_read(decoded, txn, &index) == 1);
  CHECK(index == 9);
  ytransaction_commit(txn);

  ysticky_index_destroy(pos);
  ysticky_index_destroy(decoded);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

static void test_snapshot() {
  YOptions opts{};
  opts.skip_gc = 1;  // snapshots need skip_gc (reference lib.rs:410-417)
  opts.should_load = 1;
  opts.encoding = Y_OFFSET_UTF16;
  YDoc *doc = ydoc_new_with_options(opts);
  Branch *txt = ytext(doc, "t");

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "state one", nullptr);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(doc);
  YBinary snapshot = ytransaction_snapshot(txn);
  CHECK(snapshot.data != nullptr);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 9, " and two", nullptr);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(doc);
  YBinary historic = ytransaction_encode_state_from_snapshot_v1(
      txn, snapshot.data, (uint32_t)snapshot.len);
  CHECK(historic.data != nullptr);
  ytransaction_commit(txn);

  YDoc *replica = ydoc_new();
  Branch *rt = ytext(replica, "t");
  txn = ydoc_write_transaction(replica, 0, nullptr);
  CHECK(ytransaction_apply(txn, historic.data, (uint32_t)historic.len) == 0);
  CHECK_STR(ytext_string(rt, txn), "state one");
  ytransaction_commit(txn);

  ybinary_destroy(snapshot);
  ybinary_destroy(historic);
  ybranch_destroy(rt);
  ybranch_destroy(txt);
  ydoc_destroy(replica);
  ydoc_destroy(doc);
}

static void test_v2_roundtrip() {
  YDoc *a = ydoc_new();
  Branch *ta = ytext(a, "t");
  YTransaction *txn = ydoc_write_transaction(a, 0, nullptr);
  ytext_insert(ta, txn, 0, "v2 payload", nullptr);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(a);
  YBinary diff = ytransaction_state_diff_v2(txn, nullptr, 0);
  CHECK(diff.data != nullptr);
  ytransaction_commit(txn);

  YDoc *b = ydoc_new();
  Branch *tb = ytext(b, "t");
  txn = ydoc_write_transaction(b, 0, nullptr);
  CHECK(ytransaction_apply_v2(txn, diff.data, (uint32_t)diff.len) == 0);
  CHECK_STR(ytext_string(tb, txn), "v2 payload");
  ytransaction_commit(txn);

  char *debug = yupdate_debug_v2(diff.data, (uint32_t)diff.len);
  CHECK(debug != nullptr);
  ystring_destroy(debug);

  ybinary_destroy(diff);
  ybranch_destroy(ta);
  ybranch_destroy(tb);
  ydoc_destroy(a);
  ydoc_destroy(b);
}

static void test_text_formatting() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "bold move", nullptr);
  ytext_format(txt, txn, 0, 4, "{\"bold\":true}");
  // formatting marks are invisible in the plain string
  CHECK_STR(ytext_string(txt, txn), "bold move");
  CHECK(ytext_len(txt, txn) == 9);
  ytext_insert(txt, txn, 9, "!", "{\"italic\":true}");
  CHECK_STR(ytext_string(txt, txn), "bold move!");
  ytransaction_commit(txn);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

static void test_clone_and_errors() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "cloned", nullptr);
  ytransaction_commit(txn);

  // yffi contract: the clone is a second handle onto the SAME instance
  YDoc *copy = ydoc_clone(doc);
  CHECK(copy != nullptr);
  CHECK(ydoc_id(copy) == ydoc_id(doc));
  Branch *ct = ytext(copy, "t");
  txn = ydoc_read_transaction(copy);
  CHECK_STR(ytext_string(ct, txn), "cloned");
  ytransaction_commit(txn);
  txn = ydoc_write_transaction(copy, 0, nullptr);
  ytext_insert(ct, txn, 6, "!", nullptr);
  ytransaction_commit(txn);
  txn = ydoc_read_transaction(doc);
  CHECK_STR(ytext_string(txt, txn), "cloned!");  // visible via the original
  ytransaction_commit(txn);

  // malformed update must fail cleanly, not crash
  txn = ydoc_write_transaction(doc, 0, nullptr);
  uint8_t garbage[] = {0xff, 0xff, 0xff, 0x01};
  CHECK(ytransaction_apply(txn, garbage, sizeof(garbage)) != 0);
  CHECK(ytpu_last_error() != nullptr);
  ytransaction_commit(txn);

  ybranch_destroy(ct);
  ybranch_destroy(txt);
  ydoc_destroy(copy);
  ydoc_destroy(doc);
}

static void test_read_transactions() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "shared", nullptr);
  ytransaction_commit(txn);

  // many read transactions may coexist on one doc
  YTransaction *r1 = ydoc_read_transaction(doc);
  YTransaction *r2 = ydoc_read_transaction(doc);
  CHECK(r1 != nullptr && r2 != nullptr);
  CHECK(ytransaction_writeable(r1) == 0);
  YBinary sv1 = ytransaction_state_vector_v1(r1);
  YBinary sv2 = ytransaction_state_vector_v1(r2);
  CHECK(sv1.len == sv2.len && sv1.data != nullptr);
  ybinary_destroy(sv1);
  ybinary_destroy(sv2);

  // writes through a read transaction are rejected
  YBinary diff = ytransaction_state_diff_v1(r1, nullptr, 0);
  CHECK(ytransaction_apply(r2, diff.data, (uint32_t)diff.len) != 0);
  CHECK(ytpu_last_error() != nullptr);
  ybinary_destroy(diff);
  ytransaction_commit(r1);
  ytransaction_commit(r2);

  // the error slot describes only the most recent call: a legitimate
  // "missing" NULL after a failure must not look like an error
  Branch *map = ymap(doc, "m");
  YTransaction *rt = ydoc_read_transaction(doc);
  CHECK(ymap_get(map, rt, "absent") == nullptr);
  CHECK(ytpu_last_error() == nullptr);
  ytransaction_commit(rt);

  ybranch_destroy(map);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

int main() {
  test_doc_lifecycle();
  test_text_basic();
  test_text_exchange();
  test_map();
  test_array();
  test_nested_types();
  test_xml();
  test_observers();
  test_undo();
  test_sticky_index();
  test_snapshot();
  test_v2_roundtrip();
  test_text_formatting();
  test_clone_and_errors();
  test_read_transactions();

  std::printf("%d checks, %d failures\n", g_checks, g_failures);
  return g_failures == 0 ? 0 : 1;
}
