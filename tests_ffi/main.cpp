// C ABI conformance tests for libytpu.
//
// Port model: the reference's C FFI suite (/root/reference/tests-ffi/main.cpp,
// 66 doctest cases incl. an exchange_updates helper :21-56). Uses a tiny
// assert harness instead of doctest (not vendored in this environment).
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "ytpu.h"

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    ++g_checks;                                                            \
    if (!(cond)) {                                                         \
      ++g_failures;                                                        \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      const char *err = ytpu_last_error();                                 \
      if (err) std::fprintf(stderr, "  last error: %s\n", err);            \
    }                                                                      \
  } while (0)

#define CHECK_STR(actual_expr, expected)                                  \
  do {                                                                    \
    char *actual__ = (actual_expr);                                       \
    CHECK(actual__ != nullptr && std::strcmp(actual__, (expected)) == 0); \
    if (actual__ && std::strcmp(actual__, (expected)) != 0)               \
      std::fprintf(stderr, "  actual: %s\n", actual__);                   \
    ystring_destroy(actual__);                                            \
  } while (0)

// reference tests-ffi/main.cpp:21-56 — bidirectional state-vector exchange
static void exchange_updates(YDoc *a, YDoc *b) {
  for (int dir = 0; dir < 2; ++dir) {
    YDoc *src = dir == 0 ? a : b;
    YDoc *dst = dir == 0 ? b : a;
    YTransaction *src_txn = ydoc_read_transaction(src);
    YTransaction *dst_txn = ydoc_write_transaction(dst, 0, nullptr);
    YBinary sv = ytransaction_state_vector_v1(dst_txn);
    YBinary diff = ytransaction_state_diff_v1(src_txn, sv.data, (uint32_t)sv.len);
    CHECK(ytransaction_apply(dst_txn, diff.data, (uint32_t)diff.len) == 0);
    ybinary_destroy(sv);
    ybinary_destroy(diff);
    ytransaction_commit(src_txn);
    ytransaction_commit(dst_txn);
  }
}

static void test_doc_lifecycle() {
  YOptions opts{};
  opts.id = 42;
  opts.guid = "doc-guid-1";
  opts.collection_id = "coll";
  opts.encoding = Y_OFFSET_UTF16;
  opts.should_load = 1;
  YDoc *doc = ydoc_new_with_options(opts);
  CHECK(doc != nullptr);
  CHECK(ydoc_id(doc) == 42);
  CHECK_STR(ydoc_guid(doc), "doc-guid-1");
  CHECK_STR(ydoc_collection_id(doc), "coll");
  CHECK(ydoc_should_load(doc) == 1);
  CHECK(ydoc_auto_load(doc) == 0);
  ydoc_destroy(doc);

  YDoc *rnd = ydoc_new();
  CHECK(rnd != nullptr);
  CHECK(ydoc_id(rnd) != 0);
  char *guid = ydoc_guid(rnd);
  CHECK(guid != nullptr && std::strlen(guid) > 0);
  ystring_destroy(guid);
  ydoc_destroy(rnd);
}

static void test_text_basic() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "text");
  CHECK(ytype_kind(txt) == Y_TEXT);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  CHECK(ytransaction_writeable(txn) == 1);
  ytext_insert(txt, txn, 0, "hello!", nullptr);
  ytext_insert(txt, txn, 5, " world", nullptr);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  CHECK_STR(ytext_string(txt, txn), "hello world!");
  CHECK(ytext_len(txt, txn) == 12);
  ytext_remove_range(txt, txn, 5, 6);
  CHECK_STR(ytext_string(txt, txn), "hello!");
  ytransaction_commit(txn);

  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

static void test_text_exchange() {
  YDoc *a = ydoc_new();
  YDoc *b = ydoc_new();
  Branch *ta = ytext(a, "t");
  Branch *tb = ytext(b, "t");

  YTransaction *txn = ydoc_write_transaction(a, 0, nullptr);
  ytext_insert(ta, txn, 0, "abc", nullptr);
  ytransaction_commit(txn);
  txn = ydoc_write_transaction(b, 0, nullptr);
  ytext_insert(tb, txn, 0, "XYZ", nullptr);
  ytransaction_commit(txn);

  exchange_updates(a, b);

  txn = ydoc_read_transaction(a);
  char *sa = ytext_string(ta, txn);
  ytransaction_commit(txn);
  txn = ydoc_read_transaction(b);
  char *sb = ytext_string(tb, txn);
  ytransaction_commit(txn);
  CHECK(sa != nullptr && sb != nullptr && std::strcmp(sa, sb) == 0);
  CHECK(sa != nullptr && std::strlen(sa) == 6);
  ystring_destroy(sa);
  ystring_destroy(sb);

  ybranch_destroy(ta);
  ybranch_destroy(tb);
  ydoc_destroy(a);
  ydoc_destroy(b);
}

static void test_map() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "map");
  CHECK(ytype_kind(map) == Y_MAP);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput num{};
  num.tag = Y_JSON_NUM;
  num.value.num = 3.5;
  ymap_insert(map, txn, "pi", &num);
  YInput str{};
  str.tag = Y_JSON_STR;
  str.value.str = "value";
  ymap_insert(map, txn, "key", &str);
  YInput arr = yinput_json_array_str("[1,2,3]");
  ymap_insert(map, txn, "list", &arr);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(doc);
  CHECK(ymap_len(map, txn) == 3);
  YOutput *pi = ymap_get(map, txn, "pi");
  CHECK(pi != nullptr && youtput_tag(pi) == Y_JSON_NUM);
  CHECK(pi != nullptr && youtput_read_float(pi) == 3.5);
  youtput_destroy(pi);
  YOutput *val = ymap_get(map, txn, "key");
  CHECK(val != nullptr && youtput_tag(val) == Y_JSON_STR);
  CHECK_STR(youtput_read_string(val), "value");
  youtput_destroy(val);
  YOutput *lst = ymap_get(map, txn, "list");
  CHECK(lst != nullptr && youtput_tag(lst) == Y_JSON_ARR);
  CHECK_STR(youtput_json(lst), "[1, 2, 3]");
  youtput_destroy(lst);
  CHECK(ymap_get(map, txn, "missing") == nullptr);
  ytransaction_commit(txn);

  // iterate
  txn = ydoc_read_transaction(doc);
  YMapIter *iter = ymap_iter(map, txn);
  int seen = 0;
  while (YMapEntry *entry = ymap_iter_next(iter)) {
    CHECK(entry->key != nullptr && entry->value != nullptr);
    ++seen;
    ymap_entry_destroy(entry);
  }
  CHECK(seen == 3);
  ymap_iter_destroy(iter);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  CHECK(ymap_remove(map, txn, "pi") == 1);
  CHECK(ymap_remove(map, txn, "pi") == 0);
  CHECK(ymap_len(map, txn) == 2);
  ymap_remove_all(map, txn);
  CHECK(ymap_len(map, txn) == 0);
  ytransaction_commit(txn);

  ybranch_destroy(map);
  ydoc_destroy(doc);
}

static void test_array() {
  YDoc *doc = ydoc_new();
  Branch *arr = yarray(doc, "array");
  CHECK(ytype_kind(arr) == Y_ARRAY);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput items[3];
  items[0].tag = Y_JSON_INT;
  items[0].value.integer = 10;
  items[1].tag = Y_JSON_STR;
  items[1].value.str = "mid";
  items[2].tag = Y_JSON_BOOL;
  items[2].value.flag = 1;
  yarray_insert_range(arr, txn, 0, items, 3);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(doc);
  CHECK(yarray_len(arr) == 3);
  YOutput *v0 = yarray_get(arr, txn, 0);
  CHECK(v0 != nullptr && youtput_read_long(v0) == 10);
  youtput_destroy(v0);
  YOutput *v1 = yarray_get(arr, txn, 1);
  CHECK_STR(youtput_read_string(v1), "mid");
  youtput_destroy(v1);
  YOutput *v2 = yarray_get(arr, txn, 2);
  CHECK(v2 != nullptr && youtput_tag(v2) == Y_JSON_BOOL);
  CHECK(v2 != nullptr && youtput_read_bool(v2) == 1);
  youtput_destroy(v2);

  YArrayIter *iter = yarray_iter(arr, txn);
  int n = 0;
  while (YOutput *item = yarray_iter_next(iter)) {
    ++n;
    youtput_destroy(item);
  }
  CHECK(n == 3);
  yarray_iter_destroy(iter);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  yarray_move(arr, txn, 0, 3);  // move the 10 to the end
  ytransaction_commit(txn);
  txn = ydoc_read_transaction(doc);
  YOutput *last = yarray_get(arr, txn, 2);
  CHECK(last != nullptr && youtput_read_long(last) == 10);
  youtput_destroy(last);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  yarray_remove_range(arr, txn, 0, 2);
  CHECK(yarray_len(arr) == 1);
  ytransaction_commit(txn);

  ybranch_destroy(arr);
  ydoc_destroy(doc);
}

static void test_nested_types() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "root");

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput nested_text{};
  nested_text.tag = Y_TEXT;
  nested_text.value.str = "inner";
  ymap_insert(map, txn, "text", &nested_text);
  YInput nested_arr = yinput_yarray_str("[1,2]");
  ymap_insert(map, txn, "arr", &nested_arr);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  YOutput *out = ymap_get(map, txn, "text");
  CHECK(out != nullptr && youtput_tag(out) == Y_TEXT);
  Branch *inner = youtput_read_ytext(out);
  CHECK(inner != nullptr);
  ytext_insert(inner, txn, 5, "!", nullptr);
  CHECK_STR(ytext_string(inner, txn), "inner!");
  ybranch_destroy(inner);
  youtput_destroy(out);

  YOutput *arr_out = ymap_get(map, txn, "arr");
  CHECK(arr_out != nullptr && youtput_tag(arr_out) == Y_ARRAY);
  Branch *inner_arr = youtput_read_yarray(arr_out);
  CHECK(inner_arr != nullptr && yarray_len(inner_arr) == 2);
  ybranch_destroy(inner_arr);
  youtput_destroy(arr_out);
  ytransaction_commit(txn);

  ybranch_destroy(map);
  ydoc_destroy(doc);
}

static void test_xml() {
  YDoc *doc = ydoc_new();
  Branch *frag = yxmlfragment(doc, "xml");
  CHECK(ytype_kind(frag) == Y_XML_FRAG);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  Branch *div = yxmlelem_insert_elem(frag, txn, 0, "div");
  CHECK(div != nullptr);
  CHECK_STR(yxmlelem_tag(div), "div");
  yxmlelem_insert_attr(div, txn, "class", "header");
  CHECK_STR(yxmlelem_get_attr(div, txn, "class"), "header");
  CHECK(yxmlelem_get_attr(div, txn, "id") == nullptr);

  Branch *txt = yxmlelem_insert_text(div, txn, 0);
  CHECK(txt != nullptr);
  yxmltext_insert(txt, txn, 0, "hi", nullptr);
  CHECK(yxmlelem_child_len(div, txn) == 1);
  CHECK_STR(yxmlelem_string(div, txn), "<div class=\"header\">hi</div>");

  Branch *p = yxmlelem_insert_elem(div, txn, 1, "p");
  CHECK(p != nullptr);
  CHECK(yxmlelem_child_len(div, txn) == 2);

  // siblings from the text node
  YOutput *sib = yxml_next_sibling(txt, txn);
  CHECK(sib != nullptr && youtput_tag(sib) == Y_XML_ELEM);
  youtput_destroy(sib);

  // tree walker from the fragment: div, text, p
  YXmlTreeWalker *walker = yxmlelem_tree_walker(frag, txn);
  int visited = 0;
  while (YOutput *node = yxmlelem_tree_walker_next(walker)) {
    ++visited;
    youtput_destroy(node);
  }
  CHECK(visited == 3);
  yxmlelem_tree_walker_destroy(walker);

  yxmlelem_remove_attr(div, txn, "class");
  CHECK(yxmlelem_get_attr(div, txn, "class") == nullptr);
  ytransaction_commit(txn);

  ybranch_destroy(p);
  ybranch_destroy(txt);
  ybranch_destroy(div);
  ybranch_destroy(frag);
  ydoc_destroy(doc);
}

struct UpdateCollector {
  std::vector<std::vector<uint8_t>> updates;
};

static void collect_update(void *state, uint32_t len, const uint8_t *bytes) {
  auto *collector = (UpdateCollector *)state;
  collector->updates.emplace_back(bytes, bytes + len);
}

static void test_observers() {
  YDoc *a = ydoc_new();
  YDoc *b = ydoc_new();
  Branch *ta = ytext(a, "t");
  Branch *tb = ytext(b, "t");

  UpdateCollector collected;
  YSubscription *sub = ydoc_observe_updates_v1(a, &collected, collect_update);
  CHECK(sub != nullptr);

  YTransaction *txn = ydoc_write_transaction(a, 0, nullptr);
  ytext_insert(ta, txn, 0, "observed", nullptr);
  ytransaction_commit(txn);
  CHECK(collected.updates.size() == 1);

  // live-replicate the captured update into b
  txn = ydoc_write_transaction(b, 0, nullptr);
  CHECK(ytransaction_apply(txn, collected.updates[0].data(),
                           (uint32_t)collected.updates[0].size()) == 0);
  CHECK_STR(ytext_string(tb, txn), "observed");
  ytransaction_commit(txn);

  yunobserve(sub);
  txn = ydoc_write_transaction(a, 0, nullptr);
  ytext_insert(ta, txn, 0, "x", nullptr);
  ytransaction_commit(txn);
  CHECK(collected.updates.size() == 1);  // no longer observing

  ybranch_destroy(ta);
  ybranch_destroy(tb);
  ydoc_destroy(a);
  ydoc_destroy(b);
}

static void test_undo() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YUndoManagerOptions opts{0};
  YUndoManager *mgr = yundo_manager(doc, &opts);
  CHECK(mgr != nullptr);
  yundo_manager_add_scope(mgr, txt);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "hello", nullptr);
  ytransaction_commit(txn);
  txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 5, " world", nullptr);
  ytransaction_commit(txn);

  CHECK(yundo_manager_can_undo(mgr) == 1);
  CHECK(yundo_manager_undo(mgr) == 1);
  txn = ydoc_read_transaction(doc);
  CHECK_STR(ytext_string(txt, txn), "hello");
  ytransaction_commit(txn);

  CHECK(yundo_manager_can_redo(mgr) == 1);
  CHECK(yundo_manager_redo(mgr) == 1);
  txn = ydoc_read_transaction(doc);
  CHECK_STR(ytext_string(txt, txn), "hello world");
  ytransaction_commit(txn);

  yundo_manager_clear(mgr);
  CHECK(yundo_manager_can_undo(mgr) == 0);

  yundo_manager_destroy(mgr);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

static void test_sticky_index() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "hello world", nullptr);

  YStickyIndex *pos = ysticky_index_from_index(txt, txn, 6, Y_ASSOC_AFTER);
  CHECK(pos != nullptr);
  CHECK(ysticky_index_assoc(pos) == Y_ASSOC_AFTER);

  YBinary encoded = ysticky_index_encode(pos);
  CHECK(encoded.data != nullptr && encoded.len > 0);
  YStickyIndex *decoded =
      ysticky_index_decode(encoded.data, (uint32_t)encoded.len);
  CHECK(decoded != nullptr);
  ybinary_destroy(encoded);

  // concurrent insert before the tracked position shifts the index
  ytext_insert(txt, txn, 0, ">> ", nullptr);
  uint32_t index = 0;
  CHECK(ysticky_index_read(pos, txn, &index) == 1);
  CHECK(index == 9);
  CHECK(ysticky_index_read(decoded, txn, &index) == 1);
  CHECK(index == 9);
  ytransaction_commit(txn);

  ysticky_index_destroy(pos);
  ysticky_index_destroy(decoded);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

static void test_snapshot() {
  YOptions opts{};
  opts.skip_gc = 1;  // snapshots need skip_gc (reference lib.rs:410-417)
  opts.should_load = 1;
  opts.encoding = Y_OFFSET_UTF16;
  YDoc *doc = ydoc_new_with_options(opts);
  Branch *txt = ytext(doc, "t");

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "state one", nullptr);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(doc);
  YBinary snapshot = ytransaction_snapshot(txn);
  CHECK(snapshot.data != nullptr);
  ytransaction_commit(txn);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 9, " and two", nullptr);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(doc);
  YBinary historic = ytransaction_encode_state_from_snapshot_v1(
      txn, snapshot.data, (uint32_t)snapshot.len);
  CHECK(historic.data != nullptr);
  ytransaction_commit(txn);

  YDoc *replica = ydoc_new();
  Branch *rt = ytext(replica, "t");
  txn = ydoc_write_transaction(replica, 0, nullptr);
  CHECK(ytransaction_apply(txn, historic.data, (uint32_t)historic.len) == 0);
  CHECK_STR(ytext_string(rt, txn), "state one");
  ytransaction_commit(txn);

  ybinary_destroy(snapshot);
  ybinary_destroy(historic);
  ybranch_destroy(rt);
  ybranch_destroy(txt);
  ydoc_destroy(replica);
  ydoc_destroy(doc);
}

static void test_v2_roundtrip() {
  YDoc *a = ydoc_new();
  Branch *ta = ytext(a, "t");
  YTransaction *txn = ydoc_write_transaction(a, 0, nullptr);
  ytext_insert(ta, txn, 0, "v2 payload", nullptr);
  ytransaction_commit(txn);

  txn = ydoc_read_transaction(a);
  YBinary diff = ytransaction_state_diff_v2(txn, nullptr, 0);
  CHECK(diff.data != nullptr);
  ytransaction_commit(txn);

  YDoc *b = ydoc_new();
  Branch *tb = ytext(b, "t");
  txn = ydoc_write_transaction(b, 0, nullptr);
  CHECK(ytransaction_apply_v2(txn, diff.data, (uint32_t)diff.len) == 0);
  CHECK_STR(ytext_string(tb, txn), "v2 payload");
  ytransaction_commit(txn);

  char *debug = yupdate_debug_v2(diff.data, (uint32_t)diff.len);
  CHECK(debug != nullptr);
  ystring_destroy(debug);

  ybinary_destroy(diff);
  ybranch_destroy(ta);
  ybranch_destroy(tb);
  ydoc_destroy(a);
  ydoc_destroy(b);
}

static void test_text_formatting() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "bold move", nullptr);
  ytext_format(txt, txn, 0, 4, "{\"bold\":true}");
  // formatting marks are invisible in the plain string
  CHECK_STR(ytext_string(txt, txn), "bold move");
  CHECK(ytext_len(txt, txn) == 9);
  ytext_insert(txt, txn, 9, "!", "{\"italic\":true}");
  CHECK_STR(ytext_string(txt, txn), "bold move!");
  ytransaction_commit(txn);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

static void test_clone_and_errors() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "cloned", nullptr);
  ytransaction_commit(txn);

  // yffi contract: the clone is a second handle onto the SAME instance
  YDoc *copy = ydoc_clone(doc);
  CHECK(copy != nullptr);
  CHECK(ydoc_id(copy) == ydoc_id(doc));
  Branch *ct = ytext(copy, "t");
  txn = ydoc_read_transaction(copy);
  CHECK_STR(ytext_string(ct, txn), "cloned");
  ytransaction_commit(txn);
  txn = ydoc_write_transaction(copy, 0, nullptr);
  ytext_insert(ct, txn, 6, "!", nullptr);
  ytransaction_commit(txn);
  txn = ydoc_read_transaction(doc);
  CHECK_STR(ytext_string(txt, txn), "cloned!");  // visible via the original
  ytransaction_commit(txn);

  // malformed update must fail cleanly, not crash
  txn = ydoc_write_transaction(doc, 0, nullptr);
  uint8_t garbage[] = {0xff, 0xff, 0xff, 0x01};
  CHECK(ytransaction_apply(txn, garbage, sizeof(garbage)) != 0);
  CHECK(ytpu_last_error() != nullptr);
  ytransaction_commit(txn);

  ybranch_destroy(ct);
  ybranch_destroy(txt);
  ydoc_destroy(copy);
  ydoc_destroy(doc);
}

static void test_read_transactions() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "shared", nullptr);
  ytransaction_commit(txn);

  // many read transactions may coexist on one doc
  YTransaction *r1 = ydoc_read_transaction(doc);
  YTransaction *r2 = ydoc_read_transaction(doc);
  CHECK(r1 != nullptr && r2 != nullptr);
  CHECK(ytransaction_writeable(r1) == 0);
  YBinary sv1 = ytransaction_state_vector_v1(r1);
  YBinary sv2 = ytransaction_state_vector_v1(r2);
  CHECK(sv1.len == sv2.len && sv1.data != nullptr);
  ybinary_destroy(sv1);
  ybinary_destroy(sv2);

  // writes through a read transaction are rejected
  YBinary diff = ytransaction_state_diff_v1(r1, nullptr, 0);
  CHECK(ytransaction_apply(r2, diff.data, (uint32_t)diff.len) != 0);
  CHECK(ytpu_last_error() != nullptr);
  ybinary_destroy(diff);
  ytransaction_commit(r1);
  ytransaction_commit(r2);

  // the error slot describes only the most recent call: a legitimate
  // "missing" NULL after a failure must not look like an error
  Branch *map = ymap(doc, "m");
  YTransaction *rt = ydoc_read_transaction(doc);
  CHECK(ymap_get(map, rt, "absent") == nullptr);
  CHECK(ytpu_last_error() == nullptr);
  ytransaction_commit(rt);

  ybranch_destroy(map);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

// --- typed event observers (reference tests-ffi main.cpp YText/YMap
// observer cases) ------------------------------------------------------------
struct TextEventCapture {
  bool fired = false;
  uint32_t delta_len = 0;
  char tag0 = 0;
  uint32_t len0 = 0;
  std::string insert0;
  std::string target_str;
};

static void on_text_event(void *state, const YTextEvent *e) {
  TextEventCapture *cap = (TextEventCapture *)state;
  cap->fired = true;
  CHECK(yevent_kind(e) == Y_TEXT);
  Branch *target = ytext_event_target(e);
  CHECK(target != nullptr);
  char *s = ytext_string(target, nullptr);
  if (s) cap->target_str = s;
  ystring_destroy(s);
  ybranch_destroy(target);
  YDelta *delta = ytext_event_delta(e, &cap->delta_len);
  if (delta && cap->delta_len > 0) {
    cap->tag0 = delta[0].tag;
    cap->len0 = delta[0].len;
    if (delta[0].insert) {
      char *ins = youtput_read_string(delta[0].insert);
      if (ins) cap->insert0 = ins;
      ystring_destroy(ins);
    }
  }
  ytext_delta_destroy(delta, cap->delta_len);
}

static void test_typed_text_observer() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  TextEventCapture cap;
  YSubscription *sub = ytext_observe(txt, &cap, on_text_event);
  CHECK(sub != nullptr);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "hello", nullptr);
  ytransaction_commit(txn);

  CHECK(cap.fired);
  CHECK(cap.delta_len == 1);
  CHECK(cap.tag0 == Y_EVENT_CHANGE_ADD);
  CHECK(cap.len0 == 5);
  CHECK(cap.insert0 == "hello");
  CHECK(cap.target_str == "hello");

  // delete from the middle → retain + delete segments
  cap = TextEventCapture{};
  txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_remove_range(txt, txn, 1, 2);
  ytransaction_commit(txn);
  CHECK(cap.fired);
  CHECK(cap.delta_len == 2);
  CHECK(cap.tag0 == Y_EVENT_CHANGE_RETAIN);
  CHECK(cap.len0 == 1);

  yunobserve(sub);
  cap = TextEventCapture{};
  txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "x", nullptr);
  ytransaction_commit(txn);
  CHECK(!cap.fired);

  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

struct MapEventCapture {
  bool fired = false;
  uint32_t keys_len = 0;
  std::string key0;
  char tag0 = 0;
  std::string new0;
};

static void on_map_event(void *state, const YMapEvent *e) {
  MapEventCapture *cap = (MapEventCapture *)state;
  cap->fired = true;
  CHECK(yevent_kind(e) == Y_MAP);
  YEventKeyChange *keys = ymap_event_keys(e, &cap->keys_len);
  if (keys && cap->keys_len > 0) {
    cap->key0 = keys[0].key ? keys[0].key : "";
    cap->tag0 = keys[0].tag;
    if (keys[0].new_value) {
      char *s = youtput_read_string(keys[0].new_value);
      if (s) cap->new0 = s;
      ystring_destroy(s);
    }
  }
  yevent_keys_destroy(keys, cap->keys_len);
}

static void test_typed_map_observer() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "m");
  MapEventCapture cap;
  YSubscription *sub = ymap_observe(map, &cap, on_map_event);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput v = yinput_string("world");
  ymap_insert(map, txn, "greeting", &v);
  ytransaction_commit(txn);

  CHECK(cap.fired);
  CHECK(cap.keys_len == 1);
  CHECK(cap.key0 == "greeting");
  CHECK(cap.tag0 == Y_EVENT_KEY_CHANGE_ADD);
  CHECK(cap.new0 == "world");

  cap = MapEventCapture{};
  txn = ydoc_write_transaction(doc, 0, nullptr);
  CHECK(ymap_remove(map, txn, "greeting") == 1);
  ytransaction_commit(txn);
  CHECK(cap.fired);
  CHECK(cap.tag0 == Y_EVENT_KEY_CHANGE_DELETE);

  yunobserve(sub);
  ybranch_destroy(map);
  ydoc_destroy(doc);
}

struct ArrayEventCapture {
  bool fired = false;
  uint32_t delta_len = 0;
  char tag0 = 0;
  uint32_t len0 = 0;
  int64_t first_value = 0;
};

static void on_array_event(void *state, const YArrayEvent *e) {
  ArrayEventCapture *cap = (ArrayEventCapture *)state;
  cap->fired = true;
  YEventChange *delta = yarray_event_delta(e, &cap->delta_len);
  if (delta && cap->delta_len > 0) {
    cap->tag0 = delta[0].tag;
    cap->len0 = delta[0].len;
    if (delta[0].values && delta[0].len > 0 && delta[0].values[0]) {
      cap->first_value = youtput_read_long(delta[0].values[0]);
    }
  }
  yevent_delta_destroy(delta, cap->delta_len);
}

static void test_typed_array_observer() {
  YDoc *doc = ydoc_new();
  Branch *arr = yarray(doc, "a");
  ArrayEventCapture cap;
  YSubscription *sub = yarray_observe(arr, &cap, on_array_event);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput items[2] = {yinput_long(11), yinput_long(22)};
  yarray_insert_range(arr, txn, 0, items, 2);
  ytransaction_commit(txn);

  CHECK(cap.fired);
  CHECK(cap.delta_len == 1);
  CHECK(cap.tag0 == Y_EVENT_CHANGE_ADD);
  CHECK(cap.len0 == 2);
  CHECK(cap.first_value == 11);

  yunobserve(sub);
  ybranch_destroy(arr);
  ydoc_destroy(doc);
}

struct DeepCapture {
  bool fired = false;
  uint32_t count = 0;
  int8_t kind0 = 0;
  uint32_t path_len = 0;
  std::string path_key0;
};

static void on_deep_event(void *state, uint32_t count,
                          const YEvent *const *events) {
  DeepCapture *cap = (DeepCapture *)state;
  cap->fired = true;
  cap->count = count;
  if (count > 0) {
    cap->kind0 = yevent_kind(events[0]);
    YPathSegment *path = ytext_event_path(events[0], &cap->path_len);
    if (path && cap->path_len > 0 && path[0].tag == Y_EVENT_PATH_KEY) {
      cap->path_key0 = path[0].value.key;
    }
    ypath_destroy(path, cap->path_len);
  }
}

static void test_deep_observer() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "root");

  // nest a text under the map, then observe deep from the map
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput nested = yinput_ytext("");
  ymap_insert(map, txn, "inner", &nested);
  ytransaction_commit(txn);

  DeepCapture cap;
  YSubscription *sub = yobserve_deep(map, &cap, on_deep_event);

  txn = ydoc_write_transaction(doc, 0, nullptr);
  YTransaction *rt = nullptr;
  YOutput *out = ymap_get(map, nullptr, "inner");
  CHECK(out != nullptr);
  Branch *inner = youtput_read_ytext(out);
  CHECK(inner != nullptr);
  ytext_insert(inner, txn, 0, "deep", nullptr);
  ytransaction_commit(txn);
  (void)rt;

  CHECK(cap.fired);
  CHECK(cap.count == 1);
  CHECK(cap.kind0 == Y_TEXT);
  CHECK(cap.path_len == 1);
  CHECK(cap.path_key0 == "inner");

  yunobserve(sub);
  ybranch_destroy(inner);
  youtput_destroy(out);
  ybranch_destroy(map);
  ydoc_destroy(doc);
}

// --- weak links (reference tests-ffi weak cases) -----------------------------
static void test_weak_links() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "m");
  Branch *arr = yarray(doc, "a");

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput v = yinput_string("payload");
  ymap_insert(map, txn, "k", &v);
  YInput nums[3] = {yinput_long(1), yinput_long(2), yinput_long(3)};
  yarray_insert_range(arr, txn, 0, nums, 3);

  // link to a map entry, store the link in the array
  YWeak *link = ymap_link(map, txn, "k");
  CHECK(link != nullptr);
  YInput wl = yinput_weak(link);
  yarray_insert_range(arr, txn, 3, &wl, 1);
  ytransaction_commit(txn);
  yweak_destroy(link);

  YOutput *out = yarray_get(arr, nullptr, 3);
  CHECK(out != nullptr);
  Branch *weak_ref = youtput_read_yweak(out);
  CHECK(weak_ref != nullptr);
  YOutput *deref = yweak_deref(weak_ref, nullptr);
  CHECK(deref != nullptr);
  CHECK_STR(youtput_read_string(deref), "payload");
  youtput_destroy(deref);

  // map entry update → link follows the live value
  txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput v2 = yinput_string("updated");
  ymap_insert(map, txn, "k", &v2);
  ytransaction_commit(txn);
  deref = yweak_deref(weak_ref, nullptr);
  CHECK(deref != nullptr);
  CHECK_STR(youtput_read_string(deref), "updated");
  youtput_destroy(deref);
  ybranch_destroy(weak_ref);
  youtput_destroy(out);

  // quote an array range and iterate it through the weak iter
  txn = ydoc_write_transaction(doc, 0, nullptr);
  YWeak *quote = yarray_quote(arr, txn, 0, 2, 0, 0); // [1,2,3] inclusive
  CHECK(quote != nullptr);
  YInput wq = yinput_weak(quote);
  yarray_insert_range(arr, txn, 4, &wq, 1);
  ytransaction_commit(txn);
  yweak_destroy(quote);

  out = yarray_get(arr, nullptr, 4);
  CHECK(out != nullptr);
  Branch *quote_ref = youtput_read_yweak(out);
  CHECK(quote_ref != nullptr);
  YWeakIter *iter = yweak_iter(quote_ref, nullptr);
  CHECK(iter != nullptr);
  int64_t expect[3] = {1, 2, 3};
  for (int i = 0; i < 3; ++i) {
    YOutput *item = yweak_iter_next(iter);
    CHECK(item != nullptr);
    if (item) CHECK(youtput_read_long(item) == expect[i]);
    youtput_destroy(item);
  }
  CHECK(yweak_iter_next(iter) == nullptr);
  yweak_iter_destroy(iter);
  ybranch_destroy(quote_ref);
  youtput_destroy(out);

  // quote a text range → yweak_string
  Branch *txt = ytext(doc, "t");
  txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "abcdef", nullptr);
  YWeak *tq = ytext_quote(txt, txn, 1, 4, 0, 0); // "bcde"
  CHECK(tq != nullptr);
  YInput wtq = yinput_weak(tq);
  yarray_insert_range(arr, txn, 5, &wtq, 1);
  ytransaction_commit(txn);
  yweak_destroy(tq);

  out = yarray_get(arr, nullptr, 5);
  Branch *text_link = out ? youtput_read_yweak(out) : nullptr;
  CHECK(text_link != nullptr);
  CHECK_STR(yweak_string(text_link, nullptr), "bcde");
  ybranch_destroy(text_link);
  youtput_destroy(out);

  ybranch_destroy(txt);
  ybranch_destroy(arr);
  ybranch_destroy(map);
  ydoc_destroy(doc);
}

// --- subdocuments over the C ABI ---------------------------------------------
struct SubdocsCapture {
  bool fired = false;
  uint32_t added = 0, removed = 0, loaded = 0;
  std::string guid0;
};

static void on_subdocs(void *state, const YSubdocsEvent *e) {
  SubdocsCapture *cap = (SubdocsCapture *)state;
  cap->fired = true;
  cap->added += e->added_len;
  cap->removed += e->removed_len;
  cap->loaded += e->loaded_len;
  if (e->added_len > 0 && e->added[0]) {
    char *guid = ydoc_guid(e->added[0]);
    if (guid) cap->guid0 = guid;
    ystring_destroy(guid);
  }
}

static void test_subdocs() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "m");
  SubdocsCapture cap;
  YSubscription *sub = ydoc_observe_subdocs(doc, &cap, on_subdocs);

  YOptions opts = yoptions();
  opts.guid = "child-doc";
  YDoc *child = ydoc_new_with_options(opts);
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput di = yinput_ydoc(child);
  ymap_insert(map, txn, "sub", &di);
  ytransaction_commit(txn);

  CHECK(cap.fired);
  CHECK(cap.added == 1);
  CHECK(cap.guid0 == "child-doc");

  txn = ydoc_write_transaction(doc, 0, nullptr);
  uint32_t n = 0;
  YDoc **subdocs = ytransaction_subdocs(txn, &n);
  CHECK(n == 1);
  if (subdocs && n == 1 && subdocs[0]) {
    CHECK_STR(ydoc_guid(subdocs[0]), "child-doc");
    ydoc_destroy(subdocs[0]);
  }
  free(subdocs);
  ytransaction_commit(txn);

  YOutput *out = ymap_get(map, nullptr, "sub");
  CHECK(out != nullptr);
  YDoc *got = youtput_read_ydoc(out);
  CHECK(got != nullptr);
  if (got) {
    CHECK_STR(ydoc_guid(got), "child-doc");
    ydoc_destroy(got);
  }
  youtput_destroy(out);

  yunobserve(sub);
  ydoc_destroy(child);
  ybranch_destroy(map);
  ydoc_destroy(doc);
}

// --- pending update introspection --------------------------------------------
static void test_pending_update() {
  // create an update with a dependency gap: apply doc-b's SECOND txn first
  YDoc *a = ydoc_new();
  YOptions opts = yoptions();
  opts.id = 7777;
  YDoc *b = ydoc_new_with_options(opts);
  Branch *bt = ytext(b, "t");

  YTransaction *txn = ydoc_write_transaction(b, 0, nullptr);
  ytext_insert(bt, txn, 0, "first", nullptr);
  ytransaction_commit(txn);
  YTransaction *rb = ydoc_read_transaction(b);
  YBinary full1 = ytransaction_state_diff_v1(rb, nullptr, 0);
  ytransaction_commit(rb);

  txn = ydoc_write_transaction(b, 0, nullptr);
  ytext_insert(bt, txn, 5, "second", nullptr);
  ytransaction_commit(txn);
  rb = ydoc_read_transaction(b);
  YBinary sv1 = {nullptr, 0};
  {
    // state vector covering only txn1: decode diff1's target state
    YDoc *tmp = ydoc_new();
    YTransaction *tt = ydoc_write_transaction(tmp, 0, nullptr);
    CHECK(ytransaction_apply(tt, full1.data, (uint32_t)full1.len) == 0);
    sv1 = ytransaction_state_vector_v1(tt);
    ytransaction_commit(tt);
    ydoc_destroy(tmp);
  }
  YBinary diff2 = ytransaction_state_diff_v1(rb, sv1.data, (uint32_t)sv1.len);
  ytransaction_commit(rb);

  // apply the dependent update first → must stash as pending
  txn = ydoc_write_transaction(a, 0, nullptr);
  CHECK(ytransaction_apply(txn, diff2.data, (uint32_t)diff2.len) == 0);
  YPendingUpdate *pending = ytransaction_pending_update(txn);
  CHECK(pending != nullptr);
  if (pending) {
    CHECK(pending->missing.len > 0);
    CHECK(pending->update_v1.len > 0);
  }
  ypending_update_destroy(pending);
  ytransaction_commit(txn);

  // then the base update → pending drains, text completes
  txn = ydoc_write_transaction(a, 0, nullptr);
  CHECK(ytransaction_apply(txn, full1.data, (uint32_t)full1.len) == 0);
  YPendingUpdate *drained = ytransaction_pending_update(txn);
  CHECK(drained == nullptr);
  ytransaction_commit(txn);

  Branch *at = ytext(a, "t");
  CHECK_STR(ytext_string(at, nullptr), "firstsecond");

  ybinary_destroy(full1);
  ybinary_destroy(sv1);
  ybinary_destroy(diff2);
  ybranch_destroy(at);
  ybranch_destroy(bt);
  ydoc_destroy(a);
  ydoc_destroy(b);
}

// --- logical branch ids -------------------------------------------------------
static void test_branch_ids() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "root-map");

  // root branch → name id
  YBranchId root_id = ybranch_id(map);
  CHECK(root_id.client_or_len < 0);
  CHECK(root_id.variant.name != nullptr);
  std::string name((const char *)root_id.variant.name,
                   (size_t)(-root_id.client_or_len));
  CHECK(name == "root-map");

  // nested branch → (client, clock) id
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput nested = yinput_yarray(nullptr, 0);
  ymap_insert(map, txn, "list", &nested);
  ytransaction_commit(txn);
  YOutput *out = ymap_get(map, nullptr, "list");
  Branch *list = out ? youtput_read_yarray(out) : nullptr;
  CHECK(list != nullptr);
  YBranchId nested_id = ybranch_id(list);
  CHECK(nested_id.client_or_len >= 0);

  // both resolve back through ybranch_get
  txn = ydoc_write_transaction(doc, 0, nullptr);
  Branch *root_back = ybranch_get(&root_id, txn);
  CHECK(root_back != nullptr);
  CHECK(ytype_kind(root_back) == Y_MAP);
  Branch *nested_back = ybranch_get(&nested_id, txn);
  CHECK(nested_back != nullptr);
  CHECK(ytype_kind(nested_back) == Y_ARRAY);
  // ytype_get finds existing roots without creating
  Branch *found = ytype_get(txn, "root-map");
  CHECK(found != nullptr);
  CHECK(ytype_get(txn, "never-defined") == nullptr);
  ytransaction_commit(txn);

  ystring_destroy((char *)root_id.variant.name);
  ybranch_destroy(root_back);
  ybranch_destroy(nested_back);
  ybranch_destroy(found);
  ybranch_destroy(list);
  youtput_destroy(out);
  ybranch_destroy(map);
  ydoc_destroy(doc);
}

// --- text chunks --------------------------------------------------------------
static void test_text_chunks() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "plainbold", nullptr);
  ytext_format(txt, txn, 5, 4, "{\"bold\":true}");
  ytransaction_commit(txn);

  uint32_t n = 0;
  YChunk *chunks = ytext_chunks(txt, nullptr, &n);
  CHECK(n == 2);
  if (chunks && n == 2) {
    CHECK_STR(youtput_read_string(chunks[0].data), "plain");
    CHECK(chunks[0].fmt_len == 0);
    CHECK_STR(youtput_read_string(chunks[1].data), "bold");
    CHECK(chunks[1].fmt_len == 1);
    if (chunks[1].fmt_len == 1) {
      CHECK(std::strcmp(chunks[1].fmt[0].key, "bold") == 0);
      CHECK(youtput_read_bool(chunks[1].fmt[0].value) == 1);
    }
  }
  ychunks_destroy(chunks, n);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

// --- xml attr iteration + parent ---------------------------------------------
static void test_xml_attrs_and_parent() {
  YDoc *doc = ydoc_new();
  Branch *frag = yxmlfragment(doc, "f");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  Branch *div = yxmlelem_insert_elem(frag, txn, 0, "div");
  CHECK(div != nullptr);
  yxmlelem_insert_attr(div, txn, "id", "main");
  yxmlelem_insert_attr(div, txn, "class", "wide");
  ytransaction_commit(txn);

  uint32_t seen = 0;
  bool saw_id = false, saw_class = false;
  YXmlAttrIter *iter = yxmlelem_attr_iter(div, nullptr);
  CHECK(iter != nullptr);
  while (YXmlAttr *attr = yxmlattr_iter_next(iter)) {
    ++seen;
    if (std::strcmp(attr->name, "id") == 0)
      saw_id = std::strcmp(attr->value, "main") == 0;
    if (std::strcmp(attr->name, "class") == 0)
      saw_class = std::strcmp(attr->value, "wide") == 0;
    yxmlattr_destroy(attr);
  }
  yxmlattr_iter_destroy(iter);
  CHECK(seen == 2);
  CHECK(saw_id);
  CHECK(saw_class);

  Branch *parent = yxmlelem_parent(div);
  CHECK(parent != nullptr);
  CHECK(ytype_kind(parent) == Y_XML_FRAG);
  ybranch_destroy(parent);

  ybranch_destroy(div);
  ybranch_destroy(frag);
  ydoc_destroy(doc);
}

// --- undo observers with meta round-trip -------------------------------------
struct UndoCapture {
  int added = 0;
  int popped = 0;
  char last_kind = -1;
  void *meta_seen = nullptr;
};

static void on_undo_added(void *state, YUndoEvent *e) {
  UndoCapture *cap = (UndoCapture *)state;
  ++cap->added;
  cap->last_kind = e->kind;
  e->meta = (void *)(intptr_t)0x1234; // user metadata attaches to the item
}

static void on_undo_popped(void *state, YUndoEvent *e) {
  UndoCapture *cap = (UndoCapture *)state;
  ++cap->popped;
  cap->last_kind = e->kind;
  cap->meta_seen = e->meta;
}

static void test_undo_observers() {
  YDoc *doc = ydoc_new();
  Branch *txt = ytext(doc, "t");
  YUndoManager *mgr = yundo_manager(doc, nullptr);
  yundo_manager_add_scope(mgr, txt);
  UndoCapture cap;
  YSubscription *sub_a = yundo_manager_observe_added(mgr, &cap, on_undo_added);
  YSubscription *sub_p =
      yundo_manager_observe_popped(mgr, &cap, on_undo_popped);

  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  ytext_insert(txt, txn, 0, "tracked", nullptr);
  ytransaction_commit(txn);
  CHECK(cap.added == 1);
  // a normal edit fires Redo for the ADDED event (yrs undo.rs:229-233)
  CHECK(cap.last_kind == Y_KIND_REDO);

  CHECK(yundo_manager_undo(mgr) == 1);
  CHECK(cap.popped == 1);
  // the meta pointer written in observe_added comes back in observe_popped
  CHECK(cap.meta_seen == (void *)(intptr_t)0x1234);
  CHECK_STR(ytext_string(txt, nullptr), "");

  yunobserve(sub_a);
  yunobserve(sub_p);
  yundo_manager_destroy(mgr);
  ybranch_destroy(txt);
  ydoc_destroy(doc);
}

// --- json collection outputs --------------------------------------------------
static void test_json_outputs() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "m");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  // recursive yffi form for the array, *_str extension for the map
  YInput elems[3] = {yinput_long(1), yinput_string("two"), yinput_float(3.5)};
  YInput arr = yinput_json_array(elems, 3);
  ymap_insert(map, txn, "list", &arr);
  YInput obj = yinput_json_map_str("{\"a\": 1, \"b\": \"bee\"}");
  ymap_insert(map, txn, "obj", &obj);
  ytransaction_commit(txn);

  YOutput *out = ymap_get(map, nullptr, "list");
  CHECK(out != nullptr);
  CHECK(youtput_tag(out) == Y_JSON_ARR);
  uint32_t n = 0;
  YOutput **items = youtput_read_json_array(out, &n);
  CHECK(n == 3);
  if (items && n == 3) {
    CHECK(youtput_read_long(items[0]) == 1);
    CHECK_STR(youtput_read_string(items[1]), "two");
    CHECK(youtput_read_float(items[2]) == 3.5);
    for (uint32_t i = 0; i < n; ++i) youtput_destroy(items[i]);
  }
  free(items);
  youtput_destroy(out);

  out = ymap_get(map, nullptr, "obj");
  CHECK(out != nullptr);
  CHECK(youtput_tag(out) == Y_JSON_MAP);
  YMapEntry **entries = youtput_read_json_map(out, &n);
  CHECK(n == 2);
  bool saw_a = false, saw_b = false;
  if (entries) {
    for (uint32_t i = 0; i < n; ++i) {
      if (!entries[i]) continue;
      if (std::strcmp(entries[i]->key, "a") == 0)
        saw_a = youtput_read_long(entries[i]->value) == 1;
      if (std::strcmp(entries[i]->key, "b") == 0) {
        char *s = youtput_read_string(entries[i]->value);
        saw_b = s && std::strcmp(s, "bee") == 0;
        ystring_destroy(s);
      }
      ymap_entry_destroy(entries[i]);
    }
  }
  free(entries);
  CHECK(saw_a);
  CHECK(saw_b);
  youtput_destroy(out);

  ybranch_destroy(map);
  ydoc_destroy(doc);
}

// --- recursive YInput (yffi parity) ------------------------------------------
static void test_recursive_yinput() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "m");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);

  // json array containing a json map containing a json array
  YInput inner_arr_elems[2] = {yinput_long(7), yinput_long(8)};
  YInput inner_map_vals[2];
  inner_map_vals[0] = yinput_json_array(inner_arr_elems, 2);
  inner_map_vals[1] = yinput_string("deep");
  const char *inner_keys_storage[2] = {"nums", "tag"};
  char *inner_keys[2] = {(char *)inner_keys_storage[0],
                         (char *)inner_keys_storage[1]};
  YInput outer_elems[2];
  outer_elems[0] = yinput_json_map(inner_keys, inner_map_vals, 2);
  outer_elems[1] = yinput_bool(1);
  YInput outer = yinput_json_array(outer_elems, 2);
  ymap_insert(map, txn, "deep", &outer);

  // a YArray prelim seeded with recursive elements
  YInput prelim_elems[3] = {yinput_long(1), yinput_long(2),
                            yinput_string("three")};
  YInput prelim = yinput_yarray(prelim_elems, 3);
  ymap_insert(map, txn, "list", &prelim);

  // a YMap prelim seeded with recursive entries
  YInput mp_vals[1] = {yinput_float(2.5)};
  char *mp_keys[1] = {(char *)"pi-ish"};
  YInput mprelim = yinput_ymap(mp_keys, mp_vals, 1);
  ymap_insert(map, txn, "dict", &mprelim);
  ytransaction_commit(txn);

  // verify the deep json value
  YOutput *out = ymap_get(map, nullptr, "deep");
  CHECK(out != nullptr);
  CHECK(youtput_tag(out) == Y_JSON_ARR);
  uint32_t n = 0;
  YOutput **items = youtput_read_json_array(out, &n);
  CHECK(n == 2);
  if (items && n == 2) {
    CHECK(youtput_tag(items[0]) == Y_JSON_MAP);
    uint32_t m = 0;
    YMapEntry **entries = youtput_read_json_map(items[0], &m);
    CHECK(m == 2);
    bool saw_nums = false;
    if (entries) {
      for (uint32_t i = 0; i < m; ++i) {
        if (!entries[i]) continue;
        if (std::string(entries[i]->key) == "nums") {
          saw_nums = true;
          uint32_t k = 0;
          YOutput **nums = youtput_read_json_array(entries[i]->value, &k);
          CHECK(k == 2);
          if (nums && k == 2) {
            CHECK(youtput_read_long(nums[0]) == 7);
            CHECK(youtput_read_long(nums[1]) == 8);
            for (uint32_t j = 0; j < k; ++j) youtput_destroy(nums[j]);
          }
          free(nums);
        }
        ymap_entry_destroy(entries[i]);
      }
    }
    free(entries);
    CHECK(saw_nums);
    CHECK(youtput_read_bool(items[1]) == 1);
    for (uint32_t i = 0; i < n; ++i) youtput_destroy(items[i]);
  }
  free(items);
  youtput_destroy(out);

  // the YArray prelim became a live shared array
  out = ymap_get(map, nullptr, "list");
  Branch *list = out ? youtput_read_yarray(out) : nullptr;
  CHECK(list != nullptr);
  CHECK(yarray_len(list) == 3);
  youtput_destroy(out);

  // the YMap prelim became a live shared map
  out = ymap_get(map, nullptr, "dict");
  Branch *dict = out ? youtput_read_ymap(out) : nullptr;
  CHECK(dict != nullptr);
  YOutput *pv = ymap_get(dict, nullptr, "pi-ish");
  CHECK(pv != nullptr && youtput_read_float(pv) == 2.5);
  youtput_destroy(pv);
  youtput_destroy(out);

  ybranch_destroy(map);
  ydoc_destroy(doc);
}

// --- by-value YOutput (yffi ABI-shape parity) --------------------------------
static void test_byvalue_youtput() {
  YDoc *doc = ydoc_new();
  Branch *map = ymap(doc, "bv");
  YTransaction *txn = ydoc_write_transaction(doc, 0, nullptr);
  YInput elems[4] = {yinput_long(7), yinput_string("str"), yinput_bool(1),
                     yinput_null()};
  YInput arr = yinput_json_array(elems, 4);
  ymap_insert(map, txn, "list", &arr);
  YInput name = yinput_string("ada");
  ymap_insert(map, txn, "name", &name);
  YInput num = yinput_float(2.25);
  ymap_insert(map, txn, "score", &num);
  ytransaction_commit(txn);

  YOutput *out = ymap_get(map, nullptr, "list");
  CHECK(out != nullptr);
  YOutputValue v = youtput_unwrap(out);
  CHECK(v.tag == Y_JSON_ARR);
  CHECK(v.len == 4);
  if (v.tag == Y_JSON_ARR && v.len == 4 && v.value.array) {
    CHECK(v.value.array[0].tag == Y_JSON_INT);
    CHECK(v.value.array[0].value.integer == 7);
    CHECK(v.value.array[1].tag == Y_JSON_STR);
    CHECK_STR(strdup(v.value.array[1].value.str), "str");  // dup: destroy frees the tree
    CHECK(v.value.array[2].tag == Y_JSON_BOOL);
    CHECK(v.value.array[2].value.flag == 1);
    CHECK(v.value.array[3].tag == Y_JSON_NULL);
  }
  youtput_value_destroy(v);
  youtput_destroy(out);

  out = ymap_get(map, nullptr, "score");
  CHECK(out != nullptr);
  v = youtput_unwrap(out);
  CHECK(v.tag == Y_JSON_NUM);
  CHECK(v.len == 1);
  CHECK(v.value.num == 2.25);
  youtput_value_destroy(v);
  youtput_destroy(out);

  // a shared-type leaf comes back as a usable opaque Branch handle
  YInput nested = yinput_ytext("hello");
  txn = ydoc_write_transaction(doc, 0, nullptr);
  ymap_insert(map, txn, "t", &nested);
  ytransaction_commit(txn);
  out = ymap_get(map, nullptr, "t");
  CHECK(out != nullptr);
  v = youtput_unwrap(out);
  CHECK(v.tag == Y_TEXT);
  if (v.tag == Y_TEXT && v.value.y_type) {
    char *s = ytext_string(v.value.y_type, nullptr);
    CHECK_STR(s, "hello");
  }
  youtput_value_destroy(v);
  youtput_destroy(out);
  ydoc_destroy(doc);
}

int main() {
  test_doc_lifecycle();
  test_text_basic();
  test_text_exchange();
  test_map();
  test_array();
  test_nested_types();
  test_xml();
  test_observers();
  test_undo();
  test_sticky_index();
  test_snapshot();
  test_v2_roundtrip();
  test_text_formatting();
  test_clone_and_errors();
  test_read_transactions();
  test_typed_text_observer();
  test_typed_map_observer();
  test_typed_array_observer();
  test_deep_observer();
  test_weak_links();
  test_subdocs();
  test_pending_update();
  test_branch_ids();
  test_text_chunks();
  test_xml_attrs_and_parent();
  test_undo_observers();
  test_json_outputs();
  test_recursive_yinput();
  test_byvalue_youtput();

  std::printf("%d checks, %d failures\n", g_checks, g_failures);
  return g_failures == 0 ? 0 : 1;
}
