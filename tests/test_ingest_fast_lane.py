"""BatchIngestor.apply_bytes — the raw-bytes fast lane.

Eligible docs ship V1 wire bytes straight to the device (decode +
integrate on-chip); ineligible docs (pending stashes, out-of-order
arrival, host-only content) take the exact host lane. Oracle: a host
`Doc` replaying the same payloads, plus `apply()` equivalence.
"""

import numpy as np
import pytest

from ytpu.core import Doc
from ytpu.models.batch_doc import get_string
from ytpu.models.ingest import BatchIngestor
from ytpu.native import available as native_available


def _edit_log(ops, client_id=1, root="text"):
    doc = Doc(client_id=client_id)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text(root)
    for tag, pos, arg in ops:
        with doc.transact() as txn:
            if tag == "i":
                txt.insert(txn, pos, arg)
            else:
                txt.remove_range(txn, pos, arg)
    return log, txt.get_string()


def _flags_clean(ing):
    f = getattr(ing, "_last_fast_flags", None)
    if f is None:
        return True
    from ytpu.ops.decode_kernel import FLAG_ERRORS

    return (np.asarray(f) & FLAG_ERRORS == 0).all()


needs_native = pytest.mark.skipif(
    not native_available(), reason="native codec unavailable"
)


@needs_native
def test_fast_lane_in_order_stream():
    ops = [("i", 0, "hello"), ("i", 5, " world"), ("d", 2, 3), ("i", 4, "🙂π")]
    log, expect = _edit_log(ops)
    ing = BatchIngestor(n_docs=2, capacity=256)
    for p in log:
        ing.apply_bytes([p, p])
        assert _flags_clean(ing)
    assert ing.fast_docs == 2 * len(log)
    assert ing.slow_docs == 0
    assert int(np.asarray(ing.state.error).max()) == 0
    assert get_string(ing.state, 0, ing.payloads) == expect
    assert get_string(ing.state, 1, ing.payloads) == expect
    # mirror must match the real state vector
    u = Doc(client_id=1)
    for p in log:
        u.apply_update_v1(p)
    assert dict(ing.svs[0].clocks) == dict(u.state_vector().clocks)


@needs_native
def test_out_of_order_takes_slow_lane_and_stashes():
    ops = [("i", 0, "abc"), ("i", 3, "def"), ("i", 6, "ghi")]
    log, expect = _edit_log(ops)
    ing = BatchIngestor(n_docs=1, capacity=256)
    ing.apply_bytes([log[0]])  # fast
    ing.apply_bytes([log[2]])  # gap → slow lane, stashes
    assert ing.pending_update(0) is not None
    ing.apply_bytes([log[1]])  # fills the gap, drains the stash
    assert ing.pending_update(0) is None
    assert get_string(ing.state, 0, ing.payloads) == expect
    assert int(np.asarray(ing.state.error).max()) == 0
    assert ing.slow_docs >= 1 and ing.fast_docs >= 1


@needs_native
def test_mixed_lanes_one_step():
    """Doc 0 rides fast; doc 1 (a WeakRef branch — host-resolved link
    source) rides slow — same step. (Plain maps AND nested shared types
    now decode on device; WeakRef is the remaining host-only type.)"""
    from ytpu.types.weak import quote_range

    log0, expect0 = _edit_log([("i", 0, "fast lane")])
    d = Doc(client_id=7)
    t1 = d.get_text("src")
    with d.transact() as txn:
        t1.insert(txn, 0, "quote me")
    log1 = []
    d.observe_update_v1(lambda p, o, t: log1.append(p))
    with d.transact() as txn:
        q = quote_range(t1, txn, 1, 4)
        d.get_array("links").insert(txn, 0, q)
    ing = BatchIngestor(n_docs=2, capacity=256)
    ing.apply_bytes([log0[0], log1[0]])
    assert ing.fast_docs == 1 and ing.slow_docs == 1
    assert get_string(ing.state, 0, ing.payloads) == expect0
    assert int(np.asarray(ing.state.error).max()) == 0


@needs_native
def test_map_rows_ride_fast_lane():
    """Map rows (parent_sub keys), ContentAny scalars, and overwrites all
    decode + integrate on device (VERDICT r1 #5: B3-style map fan-in)."""
    from ytpu.models.batch_doc import get_map

    d = Doc(client_id=7)
    log = []
    d.observe_update_v1(lambda p, o, t: log.append(p))
    m = d.get_map("m")
    with d.transact() as txn:
        m.insert(txn, "name", "alice")
    with d.transact() as txn:
        m.insert(txn, "age", 31)
    with d.transact() as txn:
        m.insert(txn, "name", "bob")  # overwrite tombstones the loser
    with d.transact() as txn:
        m.insert(txn, "score", 2.5)
    with d.transact() as txn:
        m.insert(txn, "flags", [True, None, 2.5])  # array value: tokenized
    with d.transact() as txn:
        m.insert(txn, "obj", {"k": [1]})  # nested-in-object: host lane
    with d.transact() as txn:
        m.remove(txn, "age")
    ing = BatchIngestor(n_docs=1, capacity=256)
    for p in log:
        ing.apply_bytes([p])
        assert _flags_clean(ing)
    # everything rides fast except the map-valued (recursive) update
    assert ing.fast_docs == len(log) - 1
    assert ing.slow_docs == 1
    got = get_map(ing.state, 0, ing.payloads, ing.enc.keys)
    assert got == {
        "name": "bob",
        "score": 2.5,
        "flags": [True, None, 2.5],
        "obj": {"k": [1]},
    }


@needs_native
def test_equivalence_with_host_lane():
    """apply_bytes and apply produce identical device state + renderings."""
    import random

    rng = random.Random(11)
    ops = []
    length = 0
    for _ in range(60):
        if length > 8 and rng.random() < 0.3:
            pos = rng.randint(0, length - 2)
            n = rng.randint(1, 2)
            ops.append(("d", pos, n))
            length -= n
        else:
            w = "".join(rng.choice("abcd éπ🙂") for _ in range(rng.randint(1, 5)))
            ops.append(("i", rng.randint(0, length), w))
            length += len(w)
    log, expect = _edit_log(ops)

    fast = BatchIngestor(n_docs=2, capacity=1024)
    slow = BatchIngestor(n_docs=2, capacity=1024)
    for p in log:
        fast.apply_bytes([p, None])
        slow.apply([p, None])
    assert get_string(fast.state, 0, fast.payloads) == expect
    assert get_string(slow.state, 0, slow.enc.payloads) == expect
    assert dict(fast.svs[0].clocks) == dict(slow.svs[0].clocks)
    assert int(np.asarray(fast.state.error).max()) == 0


@needs_native
def test_big_client_id_rides_fast_lane():
    """Real Yjs client ids (random 53-bit) resolve through the device
    varint-byte hash table — no host fallback (VERDICT r1: B4.2 lane)."""
    log, expect = _edit_log(
        [("i", 0, "big"), ("i", 3, " ids"), ("d", 0, 1)], client_id=2**40 + 7
    )
    ing = BatchIngestor(n_docs=1, capacity=128)
    for p in log:
        ing.apply_bytes([p])
        assert _flags_clean(ing)
    assert ing.fast_docs == len(log) and ing.slow_docs == 0
    assert get_string(ing.state, 0, ing.payloads) == expect
    u = Doc(client_id=1)
    for p in log:
        u.apply_update_v1(p)
    assert dict(ing.svs[0].clocks) == dict(u.state_vector().clocks)


@needs_native
def test_multi_client_in_order_rides_fast():
    """A merged two-client update whose wire order is causally valid."""
    d1 = Doc(client_id=1)
    d2 = Doc(client_id=2)
    with d1.transact() as txn:
        d1.get_text("text").insert(txn, 0, "aa")
    d2.apply_update_v1(d1.encode_state_as_update_v1())
    with d2.transact() as txn:
        d2.get_text("text").insert(txn, 2, "bb")
    full = d2.encode_state_as_update_v1()
    expect = d2.get_text("text").get_string()

    ing = BatchIngestor(n_docs=1, capacity=128)
    ing.apply_bytes([full])
    assert int(np.asarray(ing.state.error).max()) == 0
    assert get_string(ing.state, 0, ing.payloads) == expect
    # wire order is clients-descending; client 2's blocks depend on client
    # 1's — eligibility must have checked order, whichever lane ran
    if ing.fast_docs:
        assert _flags_clean(ing)


@needs_native
def test_checkpoint_roundtrip_with_fast_refs(tmp_path):
    from ytpu.models.checkpoint import load_ingestor, save_ingestor

    log, expect = _edit_log([("i", 0, "persist"), ("i", 7, " me 🙂")])
    ing = BatchIngestor(n_docs=1, capacity=128)
    for p in log:
        ing.apply_bytes([p])
    assert ing.fast_docs == len(log)
    path = str(tmp_path / "ckpt")
    save_ingestor(path, ing)
    restored = load_ingestor(path)
    assert get_string(restored.state, 0, restored.payloads) == expect
    # the restored ingestor keeps ingesting on both lanes
    more, expect2 = _edit_log(
        [("i", 0, "persist"), ("i", 7, " me 🙂"), ("i", 0, "X")]
    )
    restored.apply_bytes([more[2]])
    assert get_string(restored.state, 0, restored.payloads) == expect2


@needs_native
def test_redelivered_update_is_idempotent_on_fast_lane():
    log, expect = _edit_log([("i", 0, "once"), ("i", 4, " twice")])
    ing = BatchIngestor(n_docs=1, capacity=128)
    ing.apply_bytes([log[0]])
    ing.apply_bytes([log[1]])
    ing.apply_bytes([log[1]])  # exact re-send
    assert int(np.asarray(ing.state.error).max()) == 0
    assert get_string(ing.state, 0, ing.payloads) == expect


@needs_native
def test_encode_diff_after_fast_lane_roundtrips():
    """Rows ingested via the fast lane carry chunked (<= -2) refs; the
    device diff encoder must resolve them through the ingestor's payload
    view, producing a wire update a fresh host doc can apply."""
    from ytpu.models.batch_doc import encode_diff_batch, finish_encode_diff

    log, expect = _edit_log([("i", 0, "chunky"), ("i", 6, " refs 🙂")])
    ing = BatchIngestor(n_docs=1, capacity=128)
    for p in log:
        ing.apply_bytes([p])
    assert ing.fast_docs == len(log)

    n_clients = max(8, len(ing.enc.interner))
    remote = np.zeros((1, n_clients), dtype=np.int32)  # empty remote SV
    import jax.numpy as jnp

    ship, offsets, _local_sv, deleted = map(
        np.asarray,
        encode_diff_batch(ing.state, jnp.asarray(remote), n_clients),
    )
    payload = finish_encode_diff(
        ing.state, 0, ship, offsets, deleted, ing.enc, ing.payloads
    )
    fresh = Doc(client_id=77)
    fresh.apply_update_v1(payload)
    assert fresh.get_text("text").get_string() == expect


@needs_native
def test_get_diff_over_mixed_lane_state():
    """Formatted text renders correct diff runs through the fast lane:
    format marks and plain inserts both decode on device (wire refs) and
    get_diff resolves format key/value pairs from the retained bytes."""
    from ytpu.models.batch_doc import get_diff

    doc = Doc(client_id=3)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    t = doc.get_text("text")
    with doc.transact() as txn:
        t.insert(txn, 0, "plain ")           # fast lane
    with doc.transact() as txn:
        t.insert_with_attributes(txn, 6, "bold", {"b": True})  # slow lane
    with doc.transact() as txn:
        t.insert(txn, 10, " tail")           # fast lane

    ing = BatchIngestor(n_docs=1, capacity=256)
    for p in log:
        ing.apply_bytes([p])
    # format marks now decode on device too: the whole stream rides fast
    assert ing.fast_docs == len(log) and ing.slow_docs == 0
    expect = doc.get_text("text").diff()
    got = get_diff(ing.state, 0, ing.payloads)
    assert got == expect, f"{got!r} != {expect!r}"


@needs_native
def test_delete_only_steps_retain_no_wire_bytes():
    log, _ = _edit_log([("i", 0, "abcdef"), ("d", 1, 3), ("d", 0, 2)])
    ing = BatchIngestor(n_docs=1, capacity=128)
    ing.apply_bytes([log[0]])
    after_insert = ing.payloads.total_bytes
    assert after_insert > 0
    ing.apply_bytes([log[1]])  # delete-only update: no string refs
    ing.apply_bytes([log[2]])
    assert ing.payloads.total_bytes == after_insert


@needs_native
def test_degenerate_wire_shapes_no_wedge():
    """Wire-legal degenerate updates (many empty ds-client sections; many
    client sections holding only Skip runs) must not wedge the batch —
    they either route to the slow lane or decode clean on device with a
    section-aware step budget (ADVICE r1, medium)."""
    from ytpu.encoding.lib0 import Writer

    # (a) zero block sections + 40 empty ds-client sections → slow lane
    w = Writer()
    w.write_var_uint(0)
    w.write_var_uint(40)
    for c in range(40):
        w.write_var_uint(c + 1)
        w.write_var_uint(0)
    empty_ds = w.to_bytes()

    # (b) 30 client sections, each a single Skip run → fast lane, but the
    # section count exceeds the emitted-row count (0) by far
    w = Writer()
    w.write_var_uint(30)
    for c in range(30):
        w.write_var_uint(1)
        w.write_var_uint(c + 100)
        w.write_var_uint(0)
        w.write_u8(10)  # BLOCK_SKIP
        w.write_var_uint(5)
    w.write_var_uint(0)
    skip_heavy = w.to_bytes()

    ing = BatchIngestor(n_docs=1, capacity=128)
    ing.apply_bytes([empty_ds])
    assert _flags_clean(ing)
    ing.apply_bytes([skip_heavy])
    assert _flags_clean(ing)
    assert int(np.asarray(ing.state.error).max()) == 0

    # the engine still works afterwards
    log, expect = _edit_log([("i", 0, "still alive")])
    for p in log:
        ing.apply_bytes([p])
    assert get_string(ing.state, 0, ing.payloads) == expect


@needs_native
def test_fast_lane_flag_recovery(monkeypatch):
    """If the device decoder flags a lane the host pre-scan validated, the
    ingestor must rewind the mirror SV and replay that doc through the
    host lane — converging instead of raising (ADVICE r1, medium)."""
    import jax.numpy as jnp

    from ytpu.ops import decode_kernel as dk

    real = dk.decode_updates_v1
    hits = {"n": 0}

    def sabotage(buf, lens, max_rows, max_dels, **kw):
        stream, flags = real(buf, lens, max_rows, max_dels, **kw)
        if hits["n"] == 0:
            hits["n"] = 1
            flags = flags | jnp.full_like(flags, dk.FLAG_MALFORMED)
            stream = stream._replace(
                valid=jnp.zeros_like(stream.valid),
                del_valid=jnp.zeros_like(stream.del_valid),
            )
        return stream, flags

    monkeypatch.setattr(dk, "decode_updates_v1", sabotage)
    log, expect = _edit_log([("i", 0, "hello"), ("i", 5, " world")])
    ing = BatchIngestor(n_docs=1, capacity=128)
    for p in log:
        ing.apply_bytes([p])
    assert hits["n"] == 1
    assert ing.fast_recoveries == 1
    assert get_string(ing.state, 0, ing.payloads) == expect
    u = Doc(client_id=9)
    for p in log:
        u.apply_update_v1(p)
    assert dict(ing.svs[0].clocks) == dict(u.state_vector().clocks)


@needs_native
def test_b3_style_map_fan_in_zero_host_fallbacks():
    """B3 micro-bench shape (yrs/benches/benches.rs:536-551): N clients
    each commit one transaction against a shared map/array doc; every
    update must ride the raw-bytes fast lane (VERDICT r1 #5 done
    criterion). Covers B3.1 (map num), B3.2 (flat object values —
    depth-1 Any objects decode on device since r3), B3.3 (map string),
    B3.4 (array insert)."""
    from ytpu.models.batch_doc import get_map

    n_clients = 24
    base = Doc(client_id=999)
    snapshot = base.encode_state_as_update_v1()
    payloads = []
    for i in range(n_clients):
        d = Doc(client_id=1000 + i)
        d.apply_update_v1(snapshot)
        log = []
        d.observe_update_v1(lambda p, o, t, log=log: log.append(p))
        m = d.get_map("map")
        with d.transact() as txn:
            if i % 4 == 0:
                m.insert(txn, f"n{i}", i)  # B3.1
            elif i % 4 == 1:
                m.insert(txn, f"o{i}", {"x": i, "y": f"v{i}"})  # B3.2
            elif i % 4 == 2:
                m.insert(txn, f"s{i}", f"val-{i}")  # B3.3
            else:
                m.insert(txn, f"a{i}", [i, i + 1])  # B3.4-ish
        payloads.append(log[-1])

    ing = BatchIngestor(n_docs=1, capacity=512)
    oracle = Doc(client_id=1)
    for p in payloads:
        ing.apply_bytes([p])
        assert _flags_clean(ing)
        oracle.apply_update_v1(p)
    assert ing.fast_docs == n_clients, "a B3 update fell back to host"
    assert ing.slow_docs == 0
    got = get_map(ing.state, 0, ing.payloads, ing.enc.keys)
    assert got == oracle.get_map("map").to_json()


def test_nested_types_ride_fast_lane():
    """ContentType rows (nested shared types) now decode on device: a map
    tenant holding a nested YText rides the raw-bytes lane end to end —
    fast_docs counts it, the tree renders, and the diff round-trips
    (north-star config #4 tenants; VERDICT r2 weak #4)."""
    from ytpu.core.state_vector import StateVector
    from ytpu.models.batch_doc import encode_diff_batch, finish_encode_diff
    from ytpu.types.shared import TextPrelim

    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    m = doc.get_map("root")
    with doc.transact() as txn:
        m.insert(txn, "title", "plain value")
    with doc.transact() as txn:
        m.insert(txn, "body", TextPrelim("nested"))
    nested = m.get("body")
    with doc.transact() as txn:
        nested.insert(txn, 6, " text")

    ing = BatchIngestor(1, 128)
    for p in log:
        ing.apply_bytes([p])
    assert ing.fast_docs == len(log), (ing.fast_docs, ing.slow_docs)
    assert int(np.asarray(ing.state.error).max()) == 0

    from ytpu.models.batch_doc import get_tree

    tree = get_tree(
        ing.state, 0, ing.payloads, ing.enc.keys, interner=ing.enc.interner
    )
    assert tree["map"]["title"] == "plain value"
    assert tree["map"]["body"] == "nested text"

    # serving: the diff re-applies on a fresh host doc with the nested
    # type intact (wire ContentType spans re-emitted verbatim)
    import jax.numpy as jnp

    n_clients = 2
    remote = np.zeros((1, n_clients), dtype=np.int32)
    ship, offsets, _loc, deleted = encode_diff_batch(
        ing.state, jnp.asarray(remote), n_clients
    )
    payload = finish_encode_diff(
        ing.state,
        0,
        np.asarray(ship),
        np.asarray(offsets),
        np.asarray(deleted),
        ing.enc,
        ing.payloads,
        root_name="root",
    )
    d = Doc(client_id=9)
    d.apply_update_v1(payload)
    got = d.get_map("root")
    assert got.get("title") == "plain value"
    assert got.get("body").get_string() == "nested text"


@needs_native
def test_fast_lane_multi_root_doc():
    """Multi-root docs (doc.rs:156-228, the reference's normal shape) ride
    the FAST lane: the wire prescan registers root names, non-primary
    roots anchor through BLOCK_ROOT_ANCHOR rows, and the device decode
    resolves them via the key table (p_root) with zero host fallbacks."""
    from ytpu.models.batch_doc import (
        encode_diff_batch,
        finish_encode_diff_batch,
        get_tree,
    )

    doc = Doc(client_id=3)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    body = doc.get_text("body")
    title = doc.get_text("title")
    meta = doc.get_map("meta")
    with doc.transact() as txn:
        body.insert(txn, 0, "content here")
    with doc.transact() as txn:
        title.insert(txn, 0, "A Title")
    with doc.transact() as txn:
        meta.insert(txn, "lang", "en")
    with doc.transact() as txn:
        title.insert(txn, 7, "?")
        body.insert(txn, 0, "* ")

    ing = BatchIngestor(n_docs=2, capacity=256)
    for p in log:
        ing.apply_bytes([p, p])
        assert _flags_clean(ing)
    assert int(np.asarray(ing.state.error).max()) == 0
    # everything after the first update (which creates the primary) should
    # stay on the fast lane — anchors resolve on device
    assert ing.fast_docs == 2 * len(log)
    assert ing.primary_roots[0] == "body"
    assert get_string(ing.state, 0, ing.payloads) == body.get_string()
    for d in (0, 1):
        tree = get_tree(
            ing.state, d, ing.payloads, ing.enc.keys, interner=ing.enc.interner
        )
        assert tree["roots"]["title"]["seq"] == list("A Title?")
        assert tree["roots"]["meta"]["map"] == {"lang": "en"}

    # serving: a fresh replica reconstructs ALL roots from the device diff
    import jax.numpy as jnp

    C = max(8, len(ing.enc.interner))
    remote = np.zeros((2, C), dtype=np.int32)
    ship, offsets, _loc, deleted = encode_diff_batch(
        ing.state, jnp.asarray(remote), C
    )
    payloads = finish_encode_diff_batch(
        ing.state, [0, 1], ship, offsets, deleted, ing.enc,
        payloads=ing.payloads, root_name="body",
    )
    for p in payloads:
        d = Doc(client_id=77)
        d.apply_update_v1(p)
        assert d.get_text("body").get_string() == body.get_string()
        assert d.get_text("title").get_string() == "A Title?"
        assert d.get_map("meta").to_json() == {"lang": "en"}
