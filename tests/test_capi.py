"""Build + run the C ABI conformance suite (tests_ffi/main.cpp).

Port model: the reference runs its C FFI tests as a separate doctest binary
against the cbindgen header (/root/reference/.github/workflows/main.yml:79-111,
tests-ffi/main.cpp). Here pytest builds libytpu_capi.so + the test binary
with g++ and asserts a clean exit.
"""

import os
import shutil
import subprocess
import sys
import sysconfig

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "ytpu", "native")
TESTS_FFI = os.path.join(REPO, "tests_ffi")
TEST_BIN = os.path.join(TESTS_FFI, "test_main")


@pytest.fixture(scope="module")
def capi_binary():
    if shutil.which("g++") is None:
        pytest.skip("g++ unavailable")
    from ytpu.native import build_capi

    lib = build_capi()
    if lib is None:
        pytest.skip("libytpu_capi.so build failed (no libpython?)")
    src = os.path.join(TESTS_FFI, "main.cpp")
    header = os.path.join(NATIVE, "include", "ytpu.h")
    if not os.path.exists(TEST_BIN) or os.path.getmtime(TEST_BIN) < max(
        os.path.getmtime(src), os.path.getmtime(lib), os.path.getmtime(header)
    ):
        subprocess.run(
            [
                "g++",
                "-O1",
                "-std=c++17",
                src,
                f"-I{os.path.join(NATIVE, 'include')}",
                f"-L{NATIVE}",
                "-lytpu_capi",
                f"-Wl,-rpath,{NATIVE}",
                "-o",
                TEST_BIN,
            ],
            check=True,
            capture_output=True,
            timeout=180,
        )
    return TEST_BIN


def test_capi_suite(capi_binary):
    env = dict(os.environ)
    # the embedded interpreter must not grab the TPU while pytest holds it
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [capi_binary], capture_output=True, text=True, timeout=300, env=env
    )
    assert proc.returncode == 0, (
        f"C ABI suite failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "0 failures" in proc.stdout
