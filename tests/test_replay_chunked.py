"""Chunked replay driver (ISSUE-4 tentpole): fixed-shape stream chunks
through the packed kernel state with BETWEEN-CHUNK device compaction under
the shared CompactionPolicy, vs the unchunked XLA lane and the host oracle.

The kernel-agnostic machinery (chunk slicing, occupancy bounds, policy,
compact/grow, sticky-error drain) is exercised on the CPU-testable
`lane="xla"` twin; the Pallas lane shares every line of the driver except
the kernel dispatch and is parity-covered on real hardware by
tests/test_pallas_kernel.py + benches/flagship_fused_chunked.py.
Interpret-mode Pallas raises NotImplementedError in this container's jax
build (seed behavior) — the fused-lane smoke SKIPS on that, never fails.
"""

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    CompactionPolicy,
    get_string,
    get_values,
    init_state,
)
from ytpu.ops.integrate_kernel import replay_stream_fused

from _fused_interpret import run_or_skip


def _capture(doc):
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    return log


def _text_stream(rounds=8, typed=20, erased=18):
    """Append-typing + contiguous range deletes: the realistic editing
    shape whose tombstones are clock- AND sequence-contiguous, so
    compaction actually reclaims them (random-position churn would leave
    unmergeable fragments — also covered, in the move test below)."""
    doc = Doc(client_id=1)
    log = _capture(doc)
    txt = doc.get_text("text")
    length = 0
    for _ in range(rounds):
        for i in range(typed):
            with doc.transact() as txn:
                txt.insert(txn, length, "abcdef"[i % 6])
            length += 1
        with doc.transact() as txn:
            txt.remove_range(txn, length - erased, erased)
        length -= erased
    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in log]
    return (
        BatchEncoder.stack_steps(steps),
        enc,
        txt.get_string(),
    )


def test_chunked_xla_compaction_parity_text():
    """Multi-chunk stream whose total row growth exceeds the chunked
    capacity: ≥1 between-chunk compaction must fire and the final text
    must match the host oracle — which IS the unchunked XLA lane's
    output (their equality is asserted suite-wide by test_batch_doc;
    compaction permutes slots, so decoded output, not raw state, is the
    byte-exact surface)."""
    stream, enc, expect = _text_stream()
    rank = enc.interner.rank_table()

    # every valid stream row integrates to one resident block and
    # deletes only tombstone, so the encoded row count is a strict lower
    # bound on uncompacted residency — no device reference run needed
    raw_rows = int(np.asarray(stream.valid).sum())

    st, stats = replay_stream_fused(
        init_state(2, 96),
        stream,
        rank,
        chunk_steps=16,
        lane="xla",
        max_capacity=96,  # growth disabled: compaction must carry it
    )
    assert raw_rows > 96, "workload must not fit without compaction"
    assert stats.compactions >= 1, stats
    assert stats.growths == 0, stats
    assert int(np.asarray(st.error).max()) == 0
    assert get_string(st, 0, enc.payloads) == expect
    assert get_string(st, 1, enc.payloads) == expect


def test_chunk_boundary_splits_after_compaction():
    """A row arriving AFTER a compaction whose origin lands mid-block of a
    squashed run: the pending split must land inside the merged block."""
    doc = Doc(client_id=1)
    log = _capture(doc)
    txt = doc.get_text("text")
    # chunk 1 territory: one sequential 12-char run (squashes to 1 block)
    for i in range(12):
        with doc.transact() as txn:
            txt.insert(txn, i, "abcdefghijkl"[i])
    # churn to trip the watermark so a compaction lands mid-stream
    for _ in range(4):
        for i in range(8):
            with doc.transact() as txn:
                txt.insert(txn, 12, "xyzwvuts"[i])
        with doc.transact() as txn:
            txt.remove_range(txn, 12, 8)
    # chunk-boundary-crossing edits: origins point mid-run (splits) and a
    # delete straddles an earlier squashed block
    for k in (3, 7, 10):
        with doc.transact() as txn:
            txt.insert(txn, k, ".")
    with doc.transact() as txn:
        txt.remove_range(txn, 2, 6)
    expect = txt.get_string()
    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in log]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()

    st, stats = replay_stream_fused(
        init_state(2, 96),
        stream,
        rank,
        chunk_steps=16,
        lane="xla",
        max_capacity=96,
        policy=CompactionPolicy(high_watermark=0.3, chunk_budget=0.7),
    )
    assert stats.compactions >= 1, stats
    assert int(np.asarray(st.error).max()) == 0
    assert get_string(st, 0, enc.payloads) == expect


def test_chunk_boundary_compaction_with_live_moves():
    """Compaction landing mid-stream with LIVE move ranges spanning the
    chunk boundary: the packed pass must remap the MV plane and keep the
    move-range planes intact for later chunks' claim recomputes.

    Shapes deliberately reuse the (chunk=16, rows=4, dels=4, C=96)
    family the tests above already compiled — one program serves the
    whole file, and distinct big programs are the suite's scarce
    resource (conftest.py LLVM-arena note)."""
    doc = Doc(client_id=1)
    log = _capture(doc)
    arr = doc.get_array("a")
    with doc.transact() as txn:
        for v in range(24):
            arr.push_back(txn, v)
    for r in range(8):
        with doc.transact() as txn:
            arr.move_range_to(txn, 1, 3, len(arr) - 1)
        for v in range(4):  # one row per txn: fits the 4-row bucket
            with doc.transact() as txn:
                arr.insert(txn, 2, 100 * r + v)
        with doc.transact() as txn:
            arr.remove_range(txn, 3, 5)
    expect = arr.to_json()
    enc = BatchEncoder(root_name="a")
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in log]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()

    st, stats = replay_stream_fused(
        init_state(2, 96),
        stream,
        rank,
        chunk_steps=16,
        lane="xla",
        max_capacity=96,
        policy=CompactionPolicy(high_watermark=0.3, chunk_budget=0.5),
    )
    assert stats.compactions >= 1, stats
    assert stats.growths == 0, stats  # pins the shape-reuse property
    assert int(np.asarray(st.error).max()) == 0
    assert get_values(st, 0, enc.payloads) == expect
    assert get_values(st, 1, enc.payloads) == expect


def test_pipeline_packed_xla_lane():
    """UpdatePipeline routes chunks into the packed chunked driver when
    the opt-in lane is selected (same policy/compaction machinery as the
    fused lane, CPU-runnable)."""
    from ytpu.models.pipeline import UpdatePipeline

    doc = Doc(client_id=1)
    log = _capture(doc)
    txt = doc.get_text("text")
    for i in range(40):
        with doc.transact() as txn:
            txt.insert(txn, i, "abcd"[i % 4])
    expect = txt.get_string()
    enc = BatchEncoder()
    pipe = UpdatePipeline(enc, n_rows=4, n_dels=4, chunk_steps=16, lane="packed_xla")
    state, n_chunks = pipe.run(init_state(2, 96), log)
    assert n_chunks == (40 + 15) // 16
    assert int(np.asarray(state.error).max()) == 0
    assert get_string(state, 0, enc.payloads) == expect


def test_pipeline_rejects_unknown_lane():
    with pytest.raises(ValueError, match="lane"):
        from ytpu.models.pipeline import UpdatePipeline

        UpdatePipeline(BatchEncoder(), 4, 4, lane="hbm")


def test_replay_stream_fused_interpret_or_skip():
    """The fused lane end-to-end in interpret mode — or a SKIP when this
    container's jax cannot interpret Pallas TPU kernels (seed behavior:
    NotImplementedError from the interpreter, not a ytpu bug)."""
    doc = Doc(client_id=1)
    log = _capture(doc)
    txt = doc.get_text("text")
    for i in range(6):
        with doc.transact() as txn:
            txt.insert(txn, i, "abcdef"[i])
    expect = txt.get_string()
    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in log]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    st, stats = run_or_skip(lambda: replay_stream_fused(
        init_state(2, 96),
        stream,
        rank,
        chunk_steps=16,
        d_block=2,
        interpret=True,
        lane="fused",
        max_capacity=96,
    ))
    assert int(np.asarray(st.error).max()) == 0
    assert get_string(st, 0, enc.payloads) == expect


def test_plan_chunks_sizes_to_policy_budget():
    from ytpu.models.replay import plan_chunks

    # flagship-shaped accounting: ~3 worst-case adds per update
    adds = np.full(200_000, 3, dtype=np.int64)
    plan = plan_chunks(adds, capacity=32768, max_chunk=8192)
    assert plan.feasible, plan
    assert plan.chunk <= 8192 and plan.chunk & (plan.chunk - 1) == 0
    assert plan.max_chunk_adds <= plan.budget
    assert plan.needs_compaction  # 600k worst-case adds >> 32768
    assert plan.n_chunks == -(-200_000 // plan.chunk)
    # a stream that fits outright plans a single max-size chunk family
    small = plan_chunks(np.full(100, 3, dtype=np.int64), capacity=32768)
    assert not small.needs_compaction
    assert small.chunk == 8192


def test_compaction_policy_watermark():
    from ytpu.models.batch_doc import DEFAULT_COMPACTION_POLICY as P

    assert P.should_compact(90, 20, 100)  # projected overflow
    assert P.should_compact(86, 1, 100)  # high-watermark tripped
    assert not P.should_compact(50, 20, 100)
    assert P.chunk_add_budget(32768) == int(0.15 * 32768)
