"""Device-engine rendering of weak links: get_tree resolves WeakRef quotes
(unquote projection, reference weak.rs:303-372) over device block columns."""

import numpy as np

from ytpu.core import Doc, Update
from ytpu.types.weak import quote_range
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    get_tree,
    init_state,
)


def capture(doc):
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    return log


def device_tree(log, capacity=256, root="a"):
    enc = BatchEncoder(root_name=root)
    state = init_state(1, capacity)
    for payload in log:
        u = Update.decode_v1(payload)
        batch = enc.build_batch([u])
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    assert int(state.error[0]) == 0
    return get_tree(state, 0, enc.payloads, enc.keys, interner=enc.interner)


def test_array_quote_renders_from_device():
    doc = Doc(client_id=1)
    log = capture(doc)
    arr = doc.get_array("a")
    with doc.transact() as txn:
        for v in [10, 20, 30, 40, 50]:
            arr.push_back(txn, v)
    with doc.transact() as txn:
        link = quote_range(arr, txn, 1, 3)  # quote [20, 30, 40]
        arr.push_back(txn, link)
    weak = doc.get_array("a").get(5)
    expect = weak.unquote()
    assert expect == [20, 30, 40]

    tree = device_tree(log)
    assert tree["seq"][:5] == [10, 20, 30, 40, 50]
    assert tree["seq"][5] == expect


def test_quote_tracks_deletions():
    doc = Doc(client_id=2)
    log = capture(doc)
    arr = doc.get_array("a")
    with doc.transact() as txn:
        for v in ["a", "b", "c", "d"]:
            arr.push_back(txn, v)
    with doc.transact() as txn:
        link = quote_range(arr, txn, 0, 3)
        arr.push_back(txn, link)
    with doc.transact() as txn:
        arr.remove_range(txn, 1, 1)  # delete "b" from inside the quote
    weak = doc.get_array("a").get(3)
    expect = weak.unquote()
    tree = device_tree(log)
    assert tree["seq"][-1] == expect


def test_quote_end_in_out_of_order_block():
    """The quote-end match must not fire on a later-clock block that merely
    precedes the end block in document order (prepend after append)."""
    doc = Doc(client_id=3)
    log = capture(doc)
    arr = doc.get_array("a")
    with doc.transact() as txn:
        arr.push_back(txn, "B")  # clock 0
    with doc.transact() as txn:
        arr.insert(txn, 0, "A")  # clock 1, document order [A, B]
    with doc.transact() as txn:
        link = quote_range(arr, txn, 0, 2)  # quote [A, B]; end id = (3, 0)
        arr.push_back(txn, link)
    expect = doc.get_array("a").get(2).unquote()
    assert expect == ["A", "B"]
    tree = device_tree(log)
    assert tree["seq"][2] == expect
