"""Array move semantics (model: reference types/array.rs move tests
+ moving.rs integration)."""

import pytest

from ytpu.core import Doc


def fill(doc, values):
    arr = doc.get_array("array")
    with doc.transact() as txn:
        n = len(arr)
        if n:
            arr.remove_range(txn, 0, n)
        arr.insert_range(txn, 0, values)
    return arr


def exchange(a, b):
    ua = a.encode_state_as_update_v1(b.state_vector())
    ub = b.encode_state_as_update_v1(a.state_vector())
    b.apply_update_v1(ua)
    a.apply_update_v1(ub)


def test_move_to_basic():
    d = Doc(client_id=1)
    arr = fill(d, [1, 2, 3])
    with d.transact() as txn:
        arr.move_to(txn, 2, 0)  # move "3" to the front
    assert arr.to_list() == [3, 1, 2]
    assert len(arr) == 3


def test_move_range_to_matches_reference():
    # reference array.rs test: move 1-2 to 4 on [0,1,2,3] -> [0,3,1,2]
    d = Doc(client_id=1)
    arr = fill(d, [0, 1, 2, 3])
    with d.transact() as txn:
        arr.move_range_to(txn, 1, 2, 4)
    assert arr.to_list() == [0, 3, 1, 2]


def test_move_to_end():
    d = Doc(client_id=1)
    arr = fill(d, ["a", "b", "c"])
    with d.transact() as txn:
        arr.move_to(txn, 0, 3)
    assert arr.to_list() == ["b", "c", "a"]


def test_move_is_noop_into_itself():
    d = Doc(client_id=1)
    arr = fill(d, [1, 2, 3])
    with d.transact() as txn:
        arr.move_to(txn, 1, 1)
        arr.move_to(txn, 1, 2)
    assert arr.to_list() == [1, 2, 3]


def test_move_syncs_to_peer():
    a = Doc(client_id=1)
    arr_a = fill(a, ["x", "y", "z"])
    b = Doc(client_id=2)
    b.apply_update_v1(a.encode_state_as_update_v1())
    with a.transact() as txn:
        arr_a.move_to(txn, 2, 0)
    b.apply_update_v1(a.encode_state_as_update_v1(b.state_vector()))
    assert b.get_array("array").to_list() == ["z", "x", "y"]
    assert arr_a.to_list() == ["z", "x", "y"]


def test_concurrent_moves_converge():
    a = Doc(client_id=1)
    arr_a = fill(a, [0, 1, 2, 3])
    b = Doc(client_id=2)
    b.apply_update_v1(a.encode_state_as_update_v1())
    arr_b = b.get_array("array")
    # both peers move element "1" to different places concurrently
    with a.transact() as txn:
        arr_a.move_to(txn, 1, 4)
    with b.transact() as txn:
        arr_b.move_to(txn, 1, 0)
    exchange(a, b)
    la, lb = arr_a.to_list(), arr_b.to_list()
    assert la == lb
    assert sorted(la) == [0, 1, 2, 3]  # nothing lost or duplicated
    assert len(la) == 4


def test_move_then_delete_moved_element():
    d = Doc(client_id=1)
    arr = fill(d, ["a", "b", "c"])
    with d.transact() as txn:
        arr.move_to(txn, 0, 3)  # b c a
    with d.transact() as txn:
        arr.remove(txn, 2)  # delete the moved "a"
    assert arr.to_list() == ["b", "c"]


def test_undo_of_move():
    from ytpu.undo import UndoManager, UndoOptions

    d = Doc(client_id=1)
    arr = fill(d, [1, 2, 3])
    mgr = UndoManager(d, arr, UndoOptions(capture_timeout_ms=0))
    with d.transact() as txn:
        arr.move_to(txn, 0, 3)
    assert arr.to_list() == [2, 3, 1]
    assert mgr.undo()
    assert arr.to_list() == [1, 2, 3]
