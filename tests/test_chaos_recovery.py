"""Fault-injected resilience (ISSUE-6): the lane-demotion ladder,
checkpointed replay recovery, poison-update quarantine, and the hardened
sync transport, all exercised through `ytpu.utils.faults` so the failure
paths run deterministically on CPU.

Every replay in this file reuses test_async_overlap's workload and its
one (n_docs=2, capacity=256, chunk=16) shape family — the compiled
decode/chunk-step/compaction programs are shared with that file (which
sorts immediately before this one), so no test here pays a fresh
big-program trace.  The fused interpret test routes through
`tests/_fused_interpret.run_or_skip` (this container's jax cannot
interpret the Pallas kernel — seed behavior) and runs LAST.
"""

import asyncio
import socket
import time

import pytest

from ytpu.native import available as native_available
from ytpu.ops import integrate_kernel as ik
from ytpu.utils import metrics
from ytpu.utils.faults import FaultError, FaultSpec, faults

from _fused_interpret import run_or_skip
from test_async_overlap import CAPACITY, CHUNK, D_BLOCK, N_DOCS, _workload

needs_native = pytest.mark.skipif(
    not native_available(), reason="native codec unavailable (plan pre-scan)"
)


@pytest.fixture(autouse=True)
def _clean_slate():
    """Armed faults and sticky lane demotions are process-global: every
    test starts and ends with both cleared so no state leaks into the
    rest of the suite."""
    faults.clear()
    ik.reset_lane_health()
    yield
    faults.clear()
    ik.reset_lane_health()


def _make(lane="xla", overlap=False, interpret=False, **kw):
    from ytpu.models.replay import FusedReplay

    _, _, plan = _workload()
    return FusedReplay(
        n_docs=N_DOCS,
        plan=plan,
        capacity=CAPACITY,
        max_capacity=CAPACITY,
        d_block=D_BLOCK,
        chunk=CHUNK,
        lane=lane,
        interpret=interpret,
        overlap=overlap,
        **kw,
    )


# --------------------------------------------------------- fault injector


def test_faults_grammar_and_determinism():
    faults.configure("dispatch.fail:lane=fused,after=2;net.delay:ms=7,n=3")
    specs = faults._specs
    assert [s.after for s in specs["dispatch.fail"]] == [2]
    assert specs["dispatch.fail"][0].args == {"lane": "fused"}
    assert specs["net.delay"][0].n == 3
    # context mismatch is not an eligible pass; match fires after `after`
    assert faults.fire("dispatch.fail", lane="xla") is None
    assert faults.fire("dispatch.fail", lane="fused") is None  # pass 1
    assert faults.fire("dispatch.fail", lane="fused") is None  # pass 2
    assert faults.fire("dispatch.fail", lane="fused") is not None  # fires
    assert faults.fire("dispatch.fail", lane="fused") is None  # n=1 spent
    # p-draws are seeded: same seed → same decision sequence
    a = FaultSpec("x", n=0, p=0.5, seed=7)
    b = FaultSpec("x", n=0, p=0.5, seed=7)
    assert [a._decide() for _ in range(32)] == [b._decide() for _ in range(32)]
    # suspended(): nothing fires inside the clean-run baseline
    faults.arm("grow.oom")
    with faults.suspended():
        assert faults.fire("grow.oom") is None
    assert faults.fire("grow.oom") is not None
    # two specs armed on one site: the pass's winner spends its fire
    # budget, the loser keeps its `n` for a later pass — so
    # "net.drop;net.drop" drops TWO frames, not one
    faults.clear()
    faults.configure("net.drop;net.drop")
    assert faults.fire("net.drop") is not None
    assert faults.fire("net.drop") is not None
    assert faults.fire("net.drop") is None


# ------------------------------------------------- lane-demotion ladder


@needs_native
def test_dispatch_fault_demotes_with_parity():
    """An injected fused-lane dispatch failure demotes the family one
    rung and retries the SAME chunk in place: the run completes on the
    packed-XLA lane with byte parity vs the serial host oracle, and the
    demotion is sticky — a later fused-lane replay of the same family
    skips the known-bad lane without any fault armed."""
    log, expect, _ = _workload()
    base = metrics.counter("lane.demotions").value
    faults.arm("dispatch.fail", lane="fused")
    r = _make(lane="fused")
    r.run(log)
    assert r.get_string(0) == expect
    assert r.stats.demotions >= 1 and r.stats.recoveries >= 1
    assert r.stats.final_lane == "xla"
    assert metrics.counter("lane.demotions").value >= base + 1
    # sticky floor: the family remembers without any armed fault
    fam = ik.lane_family(N_DOCS, D_BLOCK)
    assert ik.effective_lane(fam, "fused") == "xla"
    faults.clear()
    r2 = _make(lane="fused")
    r2.run(log)
    assert r2.get_string(0) == expect
    assert r2.stats.final_lane == "xla"
    assert r2.stats.demotions == 0  # no new failure: floor did the routing


@needs_native
def test_ladder_bottoms_out_on_host_oracle():
    """Demoting past the packed-XLA rung lands on the serial host
    oracle: slow, but the replay still completes with parity."""
    log, expect, _ = _workload()
    faults.arm("dispatch.fail", lane="xla")
    r = _make(lane="xla")
    r.run(log)
    assert r.stats.final_lane == "host"
    assert r.get_string(0) == expect
    assert r.get_string(1) == expect  # the stream is broadcast: all slots


# --------------------------------------------- checkpointed replay recovery


@needs_native
def test_kill_mid_replay_resumes_from_checkpoint():
    log, expect, _ = _workload()
    faults.arm("replay.kill", after=3)
    r = _make(checkpoint_every=2)
    r.run(log)
    assert r.get_string(0) == expect
    assert r.stats.checkpoints >= 1
    assert r.stats.resumes and r.stats.resumes[0] > 0, (
        "kill resumed from scratch, not from a chunk-boundary checkpoint"
    )


@needs_native
def test_kill_without_checkpoints_restarts_from_scratch():
    log, expect, _ = _workload()
    faults.arm("replay.kill", after=2)
    r = _make()  # checkpoint_every=0: healthy path stays zero-sync
    r.run(log)
    assert r.get_string(0) == expect
    assert r.stats.resumes == [0]


@needs_native
def test_kill_mid_overlap_resumes_with_parity():
    log, expect, _ = _workload()
    faults.arm("replay.kill", after=2)
    r = _make(overlap=True, checkpoint_every=2)
    r.run(log)
    assert r.get_string(0) == expect
    assert r.stats.resumes and r.stats.resumes[0] > 0


@needs_native
def test_continuation_fault_with_checkpoints_resumes_entry_state():
    """A second run() on a state that already carries content takes an
    entry snapshot (pos=0) when checkpointing is on: a fault before the
    first chunk-boundary checkpoint resumes from the carried state, not
    from empty (re-applying the same stream is idempotent, so parity
    proves the carried content survived)."""
    log, expect, _ = _workload()
    r = _make(checkpoint_every=4)
    r.run(log)
    assert r.get_string(0) == expect
    faults.arm("replay.kill")
    r.run(log)  # idempotent continuation: same updates re-applied
    assert r.get_string(0) == expect
    # resumed from THIS run's entry snapshot, not a stale ckpt of run 1
    assert r.stats.resumes == [0]


@needs_native
def test_continuation_fault_without_checkpoints_refuses_silent_reset():
    """With checkpointing off there is no entry snapshot: recovering a
    continuation run by rebuilding an EMPTY state would silently discard
    the content integrated before this run() — the fault must surface
    instead."""
    log, _, _ = _workload()
    r = _make()  # checkpoint_every=0
    r.run(log)
    faults.arm("replay.kill")
    with pytest.raises(ik.ReplayFault):
        r.run(log)


@needs_native
def test_recovery_budget_bounds_repeated_faults():
    """An unbounded fault (n=0) must not loop forever: after
    `max_recoveries` resume attempts the fault propagates."""
    log, _, _ = _workload()
    faults.arm("replay.kill", n=0)
    r = _make(max_recoveries=2)
    with pytest.raises(ik.ReplayFault):
        r.run(log)
    assert r.stats.recoveries == 2


# ------------------------------------------------ poison-update quarantine


@needs_native
def test_poison_update_quarantined_not_aborted():
    """A corrupted (truncated) update trips the decoder's error flags;
    with quarantine on, the update is recorded and skipped — the rest of
    the stream integrates.  The poison target is the LAST update so no
    healthy update depends on it (skipping a mid-chain update voids its
    causal dependents — that still aborts, by design)."""
    from ytpu.core import Doc

    log, _, _ = _workload()
    poison = len(log) - 1
    oracle = Doc()
    for p in log[:poison]:
        oracle.apply_update_v1(p)
    expect_m1 = oracle.get_text("text").get_string()
    base = metrics.counter("replay.quarantined").value
    faults.arm("update.corrupt", after=poison)
    r = _make(quarantine=True)
    r.run(log)
    assert r.stats.quarantined == [poison]
    assert r.get_string(0) == expect_m1
    assert metrics.counter("replay.quarantined").value == base + 1

    # same stream through the overlap lane's deferred sticky-error path
    # on the RAW ingest lane (ISSUE-7): the corruption lands in the wire
    # table, the ON-DEVICE varint decode flags the lane into the sticky
    # scalar, and deferred host re-identification quarantines the same
    # update index the serial loop names
    faults.clear()
    ik.reset_lane_health()
    faults.arm("update.corrupt", after=poison)
    r2 = _make(overlap=True, ingest="raw", quarantine=True)
    r2.run(log)
    assert r2.stats.ingest == "raw", r2.stats
    assert r2.stats.quarantined == [poison]
    assert r2.get_string(0) == expect_m1

    # and through the host-packed fallback rung (ingest="packed" — the
    # PR-5 staging the PR-6 ladder keeps): identical quarantine outcome
    faults.clear()
    ik.reset_lane_health()
    faults.arm("update.corrupt", after=poison)
    r3 = _make(overlap=True, ingest="packed", quarantine=True)
    r3.run(log)
    assert r3.stats.ingest == "packed", r3.stats
    assert r3.stats.quarantined == [poison]
    assert r3.get_string(0) == expect_m1


@needs_native
def test_poison_update_without_quarantine_still_aborts():
    log, _, _ = _workload()
    faults.arm("update.corrupt", after=len(log) - 1)
    r = _make()
    with pytest.raises(RuntimeError, match="flagged updates"):
        r.run(log)


# ------------------------------------------- overlap engine fault paths


def test_raising_producer_never_strands_consumer():
    """A staging generator that raises must shut the pipeline down
    cleanly: the error re-raises on the caller promptly (no deadlock on
    a full queue), the staged backlog is abandoned, and the engine is
    reusable afterwards."""
    from ytpu.models.replay import OverlapPipeline

    pipe = OverlapPipeline(depth=2, stage_prefix="chaos")
    consumed = []

    def produce():
        yield 1
        yield 2
        yield 3
        raise RuntimeError("staging boom")

    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="staging boom"):
        # slow consumer: the queue is full when the producer dies — the
        # old hand-rolled worker deadlocked exactly here
        pipe.run(produce(), lambda x: (time.sleep(0.05), consumed.append(x)))
    assert time.perf_counter() - t0 < 5.0, "consumer was stranded"
    # the engine survives for the retry the recovery path performs
    stats = pipe.run(iter([10, 11]), consumed.append)
    assert stats.consumed == 2 and consumed[-2:] == [10, 11]


def test_injected_staging_fault_recovers_end_to_end():
    if not native_available():
        pytest.skip("native codec unavailable (plan pre-scan)")
    log, expect, _ = _workload()
    faults.arm("stage.raise", prefix="replay")
    r = _make(overlap=True)
    r.run(log)
    assert r.get_string(0) == expect
    assert r.stats.recoveries >= 1


# ------------------------------------------------- hardened transport


def _run(coro):
    return asyncio.run(coro)


def test_whole_frame_deadline_and_reconnect_resync():
    """A peer that stalls mid-frame trips the typed FrameTimeout (the
    old first-byte timeout hung forever), and reconnect() resyncs the
    client through the state-vector handshake."""
    from ytpu.core import Doc
    from ytpu.sync.net import FrameTimeout, SyncClient, serve
    from ytpu.sync.server import SyncServer

    async def main():
        server = SyncServer()
        seed = server.doc("room")
        with seed.transact() as txn:
            seed.get_text("text").insert(txn, 0, "state")
        srv, port = await serve(server, idle_flush=0.05)
        c = SyncClient(Doc(client_id=31))
        await c.connect("127.0.0.1", port, "room")
        await c.pump(max_frames=4, timeout=0.3)
        assert c.doc.get_text("text").get_string() == "state"
        base_t = metrics.counter("net.frame_timeouts").value
        base_r = metrics.counter("net.reconnects").value
        # the next server write (this edit's broadcast) is truncated:
        # header + half the payload, then silence — a mid-frame stall
        faults.arm("net.truncate")
        with seed.transact() as txn:
            seed.get_text("text").insert(txn, 5, "!")
        with pytest.raises(FrameTimeout):
            await c.pump(max_frames=2, timeout=1.0, frame_timeout=0.4)
        assert metrics.counter("net.frame_timeouts").value == base_t + 1
        faults.clear()
        await c.reconnect()
        await c.pump(max_frames=4, timeout=0.5)
        assert c.doc.get_text("text").get_string() == "state!"
        assert metrics.counter("net.reconnects").value == base_r + 1
        await c.close()
        srv.close()
        await srv.wait_closed()

    _run(main())


def test_connect_backoff_retries_then_raises():
    from ytpu.core import Doc
    from ytpu.sync.net import SyncClient

    # a port that was just released: connects are refused immediately
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    async def main():
        base = metrics.counter("net.connect_retries").value
        c = SyncClient(Doc(client_id=32))
        t0 = time.perf_counter()
        with pytest.raises(OSError):
            await c.connect(
                "127.0.0.1", port, "room", retries=2, backoff=0.01
            )
        assert metrics.counter("net.connect_retries").value == base + 2
        assert time.perf_counter() - t0 < 5.0

    _run(main())


def test_device_server_isolates_bad_frames():
    """A malformed frame marks ONLY the offending session dead
    (net.bad_frames) — the other tenant keeps being served and nothing
    propagates into the caller."""
    from ytpu.sync.device_server import DeviceSyncServer

    srv = DeviceSyncServer(n_docs=2, capacity=256, device_authoritative=True)
    s1, _ = srv.connect_frames("a")
    s2, _ = srv.connect_frames("b")
    base = metrics.counter("net.bad_frames").value
    out = srv.receive_frames(s1, b"\xff\xff\xff\xff garbage")
    assert out == []
    assert s1.dead
    assert metrics.counter("net.bad_frames").value == base + 1
    # the healthy session still answers its handshake
    from ytpu.core.state_vector import StateVector
    from ytpu.sync.protocol import Message, SyncMessage

    step1 = Message.sync(SyncMessage.step1(StateVector({}))).encode_v1()
    replies = srv.receive_frames(s2, step1)
    assert replies and not s2.dead


def test_serve_loop_survives_poisoned_session():
    """One session whose frames blow up server-side must not take down
    the accept loop: the bad session drops, a fresh client still syncs."""
    from ytpu.core import Doc
    from ytpu.sync.net import SyncClient, serve
    from ytpu.sync.server import SyncServer

    class Poisoned(SyncServer):
        poison_ids: set = set()

        def receive_frames(self, session, data):
            if session.id in self.poison_ids:
                raise RuntimeError("server-side bug for this session")
            return super().receive_frames(session, data)

    async def main():
        server = Poisoned()
        seed = server.doc("room")
        with seed.transact() as txn:
            seed.get_text("text").insert(txn, 0, "alive")
        srv, port = await serve(server, idle_flush=0.05)
        base = metrics.counter("net.bad_frames").value
        bad = SyncClient(Doc(client_id=41))
        await bad.connect("127.0.0.1", port, "room")
        # wait for the handler to register the session, then poison it
        for _ in range(50):
            if server.tenants["room"].sessions:
                break
            await asyncio.sleep(0.02)
        server.poison_ids = {server.tenants["room"].sessions[-1].id}
        with bad.doc.transact() as txn:
            bad.doc.get_text("text").insert(txn, 0, "x")
        await bad.flush()
        await asyncio.sleep(0.2)  # server hits the poisoned path
        assert metrics.counter("net.bad_frames").value == base + 1
        # accept loop and tenant still serve a fresh client
        good = SyncClient(Doc(client_id=42))
        await good.connect("127.0.0.1", port, "room")
        await good.pump(max_frames=4, timeout=0.5)
        assert good.doc.get_text("text").get_string() == "alive"
        await bad.close()
        await good.close()
        srv.close()
        await srv.wait_closed()

    _run(main())


# ----------------------------------------------- fused interpret (LAST)


@needs_native
def test_fused_interpret_dispatch_fault_demotes():
    """The ladder under interpret-mode Pallas: the injected fault fires
    BEFORE the kernel, so this exercises the same demote-and-retry path
    the TPU worker takes on a hostile shape family.  Skips (memoized)
    where this jax build cannot interpret the fused kernel."""
    log, expect, _ = _workload()

    def thunk():
        # after=1: chunk 0 really runs the interpreted fused kernel
        # (surfacing this build's NotImplementedError for the memoized
        # skip), chunk 1 faults and demotes
        faults.arm("dispatch.fail", lane="fused", after=1)
        r = _make(lane="fused", interpret=True)
        r.run(log)
        return r

    r = run_or_skip(thunk)
    assert r.get_string(0) == expect
    assert r.stats.demotions >= 1
