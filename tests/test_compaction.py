"""Device compaction (squash + GC collapse + defrag) parity tests.

The invariant under test: compaction is semantics-preserving — replaying a
stream, compacting at arbitrary points, and continuing the replay must
produce exactly the host oracle's document (reference guarantee of
try_squash/GC at block.rs:775-799 and gc.rs)."""

import random

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    get_string,
    get_tree,
    get_values,
    init_state,
)
from ytpu.ops.compaction import compact_state, grow_state


def capture(doc: Doc):
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    return log


def replay(enc, state, payloads):
    for p in payloads:
        u = Update.decode_v1(p)
        batch = enc.build_batch([u] * state.start.shape[0])
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    return state


def text_workload(n_ops=80, seed=3):
    rng = random.Random(seed)
    doc = Doc(client_id=1)
    log = capture(doc)
    t = doc.get_text("text")
    length = 0
    for _ in range(n_ops):
        with doc.transact() as txn:
            if length > 10 and rng.random() < 0.3:
                k = rng.randint(1, 4)
                pos = rng.randint(0, length - k)
                t.remove_range(txn, pos, k)
                length -= k
            else:
                word = "".join(rng.choice("abcdef") for _ in range(rng.randint(1, 5)))
                t.insert(txn, rng.randint(0, length), word)
                length += len(word)
    return log, t.get_string()


def test_compact_preserves_text_and_shrinks():
    log, expect = text_workload()
    enc = BatchEncoder()
    state = replay(enc, init_state(2, 512), log)
    before = int(state.n_blocks[0])
    state2 = compact_state(state)
    after = int(state2.n_blocks[0])
    assert after < before, (before, after)
    assert int(state2.error.max()) == 0
    assert get_string(state2, 0, enc.payloads) == expect
    assert get_string(state2, 1, enc.payloads) == expect
    # idempotent
    state3 = compact_state(state2)
    assert int(state3.n_blocks[0]) == after
    assert get_string(state3, 0, enc.payloads) == expect


def test_compact_midstream_then_continue():
    log, expect = text_workload(n_ops=60, seed=9)
    enc = BatchEncoder()
    state = init_state(1, 512)
    cut = len(log) // 2
    state = replay(enc, state, log[:cut])
    state = compact_state(state)
    state = replay(enc, state, log[cut:])
    # compact again at the end for good measure
    state = compact_state(state)
    assert int(state.error[0]) == 0
    assert get_string(state, 0, enc.payloads) == expect


def test_compact_many_interleaved_points():
    log, expect = text_workload(n_ops=50, seed=21)
    enc = BatchEncoder()
    state = init_state(1, 512)
    for i, p in enumerate(log):
        u = Update.decode_v1(p)
        state = apply_update_batch(
            state, enc.build_batch([u]), enc.interner.rank_table()
        )
        if i % 7 == 3:
            state = compact_state(state)
    state = compact_state(state)
    assert int(state.error[0]) == 0
    assert get_string(state, 0, enc.payloads) == expect


def test_compacted_diff_applies_to_fresh_host_doc():
    from ytpu.models.batch_doc import encode_diff_batch, finish_encode_diff

    log, expect = text_workload(n_ops=40, seed=5)
    enc = BatchEncoder()
    state = compact_state(replay(enc, init_state(1, 512), log))
    C = max(8, len(enc.interner))
    remote = np.zeros((1, C), dtype=np.int32)
    import jax

    ship, offsets, local_sv, deleted = jax.tree_util.tree_map(
        np.asarray, encode_diff_batch(state, remote, C)
    )
    payload = finish_encode_diff(state, 0, ship, offsets, deleted, enc)
    replica = Doc(client_id=99)
    replica.apply_update_v1(payload)
    assert replica.get_text("text").get_string() == expect


def test_compact_with_moves():
    doc = Doc(client_id=1)
    log = capture(doc)
    arr = doc.get_array("a")
    with doc.transact() as txn:
        for v in range(8):
            arr.push_back(txn, v)
    with doc.transact() as txn:
        arr.move_range_to(txn, 2, 4, 7)
    with doc.transact() as txn:
        arr.remove_range(txn, 0, 1)
    expect = doc.get_array("a").to_json()
    enc = BatchEncoder(root_name="a")
    state = replay(enc, init_state(1, 128), log)
    state = compact_state(state)
    assert int(state.error[0]) == 0
    assert get_values(state, 0, enc.payloads) == expect


def test_compact_nested_tree():
    from ytpu.types import XmlElementPrelim

    doc = Doc(client_id=4)
    log = capture(doc)
    m = doc.get_map("m")
    with doc.transact() as txn:
        m.insert(txn, "x", 1)
        m.insert(txn, "y", "two")
    with doc.transact() as txn:
        m.insert(txn, "x", 3)  # overwrite -> tombstone
    enc = BatchEncoder(root_name="m")
    state = replay(enc, init_state(1, 128), log)
    state = compact_state(state)
    assert int(state.error[0]) == 0
    tree = get_tree(state, 0, enc.payloads, enc.keys)
    assert tree["map"] == doc.get_map("m").to_json()


def test_grow_state_continues_replay():
    log, expect = text_workload(n_ops=40, seed=13)
    enc = BatchEncoder()
    state = init_state(1, 64)
    cut = len(log) // 2
    state = replay(enc, state, log[:cut])
    state = grow_state(state, 512)
    state = replay(enc, state, log[cut:])
    assert int(state.error[0]) == 0
    assert get_string(state, 0, enc.payloads) == expect


def test_compact_plus_grow_sustains_small_capacity():
    """Periodic compaction keeps a long stream inside a small capacity."""
    log, expect = text_workload(n_ops=120, seed=17)
    enc = BatchEncoder()
    state = init_state(1, 256)
    for i, p in enumerate(log):
        u = Update.decode_v1(p)
        state = apply_update_batch(
            state, enc.build_batch([u]), enc.interner.rank_table()
        )
        if i % 16 == 15:
            state = compact_state(state)
    state = compact_state(state)
    assert int(state.error[0]) == 0
    assert get_string(state, 0, enc.payloads) == expect


def test_compact_packed_preserves_move_columns():
    """compact_packed must carry all NC columns, remapping `moved` slot
    indices through the defragment permutation (regression: the packed
    compactor once emitted only the 17 pre-move columns)."""
    from ytpu.ops.compaction import compact_packed, grow_packed
    from ytpu.ops.integrate_kernel import NC, MV, MPR, pack_state, unpack_state

    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    arr = doc.get_array("a")
    with doc.transact() as txn:
        for v in range(6):
            arr.push_back(txn, v)
    with doc.transact() as txn:
        arr.move_to(txn, 1, 5)
    with doc.transact() as txn:
        arr.remove_range(txn, 0, 1)  # a tombstone for compaction to chew

    enc = BatchEncoder(root_name="a")
    state = init_state(1, 64)
    for p in log:
        batch = enc.build_batch([Update.decode_v1(p)])
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    expect = get_values(state, 0, enc.payloads)

    cols, meta = pack_state(state)
    assert cols.shape[0] == NC
    cols2, meta2 = compact_packed(cols, meta)
    assert cols2.shape[0] == NC
    cols3, meta3 = grow_packed(cols2, meta2, 128)
    # padded slots must read as unowned, not "owned by slot 0"
    assert int(np.asarray(cols3[MV]).max(initial=-1)) < 64
    assert int(np.asarray(cols3[MV][0, 64:]).max(initial=-1)) == -1
    assert int(np.asarray(cols3[MPR][0, 64:]).max(initial=-1)) == -1
    out = unpack_state(cols3, meta3, state)
    assert get_values(out, 0, enc.payloads) == expect
    # a live move row still owns its range after defrag
    moved = np.asarray(out.blocks.moved[0])
    assert (moved >= 0).any()
