"""Performance observatory (ISSUE-17): the compile/retrace sentinel,
the unified wall-time attribution, and the label-cardinality guard.

The sentinel turns "jax silently recompiled" into an attributed,
budgetable event: every instrumented jit boundary records a per-call
shape signature, distinct signatures per program count as retraces, and
the signature DELTA names the axis that changed — so a mid-run
``YTPU_SCAN_TIER_CHEAP`` flip is caught and attributed to ``scan_plan``,
not shrugged at. The profile fold's fractions must sum to 1 of the
measured wall by construction, and the metrics registry must survive a
10k-tenant label flood by folding overflow into the reserved ``other``
label instead of growing without bound."""

import json
import os
import time
import urllib.request

import pytest

from ytpu.utils.metrics import MetricsRegistry, metrics
from ytpu.utils.phases import (
    PhaseRecorder,
    compile_storm_provider,
    phases,
)
from ytpu.utils.profile import ProfileWindow


# ---------------------------------------------------------------------------
# sentinel unit semantics (private recorder: no global state touched)
# ---------------------------------------------------------------------------


def test_sentinel_counts_retraces_and_attributes_axis():
    rec = PhaseRecorder(enabled=True)
    axes = ("shape", "dtype")
    with rec.span("prog.x", key=((4, 3), "f32"), axes=axes):
        pass
    rep = rec.compile_report()
    assert rep["events"] == 1 and rep["retraces"] == 0, rep
    # same signature again: cache hit, no new event
    with rec.span("prog.x", key=((4, 3), "f32"), axes=axes):
        pass
    assert rec.compile_report()["events"] == 1
    # a changed leading axis is a RETRACE whose delta names that axis
    with rec.span("prog.x", key=((8, 3), "f32"), axes=axes):
        pass
    rep = rec.compile_report()
    assert rep["events"] == 2 and rep["retraces"] == 1, rep
    (entry,) = rep["journal"]
    assert entry["program"] == "prog.x"
    assert [d["axis"] for d in entry["delta"]] == ["shape"]
    assert entry["delta"][0]["prev"] == repr((4, 3))
    assert entry["delta"][0]["new"] == repr((8, 3))
    # per-program attribution in the report
    assert rep["programs"] == {"prog.x": 2}


def test_compile_marker_windows_the_report():
    rec = PhaseRecorder(enabled=True)
    with rec.span("prog.w", key=(1,), axes=("k",)):
        pass
    marker = rec.compile_marker()
    assert rec.compile_report(since=marker)["events"] == 0
    with rec.span("prog.w", key=(2,), axes=("k",)):
        pass
    windowed = rec.compile_report(since=marker)
    assert windowed["events"] == 1 and windowed["retraces"] == 1
    # the full-history view still sees both sightings
    assert rec.compile_report()["events"] == 2


def test_storm_provider_budget_semantics():
    rec = PhaseRecorder(enabled=True)
    with rec.span("prog.s", key=(1,), axes=("k",)):
        pass
    marker = rec.compile_marker()
    zero = compile_storm_provider(budget=0, marker=marker, recorder=rec)
    lax = compile_storm_provider(budget=None, marker=marker, recorder=rec)
    assert not zero()["degraded"] and not lax()["degraded"]
    with rec.span("prog.s", key=(2,), axes=("k",)):
        pass
    blown = zero()
    assert blown["degraded"] and blown["storm"], blown
    assert blown["last_retrace"]["program"] == "prog.s"
    # report-only mode journals but never degrades
    assert not lax()["degraded"] and lax()["retraces"] == 1


def test_compile_retrace_fault_site():
    """Chaos can PROVE the detector fires: arming ``compile.retrace``
    perturbs the next instrumented boundary's signature with a nonce, so
    a cache-hit call journals as a retrace."""
    from ytpu.utils.faults import faults

    rec = PhaseRecorder(enabled=True)
    with rec.span("prog.fault", key=(1,), axes=("k",)):
        pass
    faults.arm("compile.retrace", n=1)
    try:
        with rec.span("prog.fault", key=(1,), axes=("k",)):
            pass
    finally:
        faults.clear()
    rep = rec.compile_report()
    assert rep["retraces"] == 1, rep
    assert rep["journal"][0]["program"] == "prog.fault"
    # the one-shot spec is spent: the same call is a cache hit again
    with rec.span("prog.fault", key=(1,), axes=("k",)):
        pass
    assert rec.compile_report()["events"] == 2


# ---------------------------------------------------------------------------
# wall-time attribution: fractions sum to 1 by construction
# ---------------------------------------------------------------------------


def test_profile_fractions_self_consistent():
    rec = PhaseRecorder(enabled=True)
    w = ProfileWindow(recorder=rec)
    w.begin()
    with rec.span("replay.stage"):
        time.sleep(0.02)
    with rec.span("encode.finish"):
        time.sleep(0.01)
    time.sleep(0.02)  # unattributed wall → idle bucket
    rep = w.report()
    assert abs(rep["fractions_sum"] - 1.0) < 1e-6, rep
    fracs = {k: v for k, v in rep.items() if k.startswith("profile_")}
    assert all(v >= 0.0 for v in fracs.values()), fracs
    assert rep["profile_staging_fraction"] > 0.0
    assert rep["profile_finisher_fraction"] > 0.0
    assert rep["profile_idle_fraction"] > 0.0
    assert rep["seconds"]["staging"] == pytest.approx(0.02, abs=0.015)


def test_profile_window_is_deltas_not_cumulative():
    rec = PhaseRecorder(enabled=True)
    with rec.span("replay.stage"):
        time.sleep(0.01)
    w = ProfileWindow(recorder=rec)
    w.begin()  # window opens AFTER the stage time above
    time.sleep(0.01)
    rep = w.report()
    assert rep["seconds"]["staging"] == pytest.approx(0.0, abs=1e-3), rep
    assert rep["profile_idle_fraction"] > 0.9, rep


def test_profile_endpoint_serves_fractions():
    from ytpu.utils.telemetry import TelemetryServer

    rec = PhaseRecorder(enabled=True)
    w = ProfileWindow(recorder=rec)
    w.begin()
    with rec.span("replay.chunk"):
        time.sleep(0.01)
    srv = TelemetryServer(port=0)
    srv.set_profile_source(w.report)
    srv.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/profile", timeout=10
        ) as r:
            assert r.status == 200
            rep = json.loads(r.read().decode())
    finally:
        srv.stop()
    assert abs(rep["fractions_sum"] - 1.0) <= 0.05, rep
    assert rep["profile_device_fraction"] > 0.0, rep


# ---------------------------------------------------------------------------
# soak integration: warmed runs score zero, a mid-run static-plan flip
# is caught and attributed
# ---------------------------------------------------------------------------


def _mini_cfg():
    from ytpu.serving import Scenario, ScenarioConfig

    return Scenario(
        ScenarioConfig(
            n_tenants=2, n_sessions=4, events_per_session=6, seed=5
        )
    )


def _fresh_server():
    from ytpu.sync.device_server import DeviceSyncServer

    return DeviceSyncServer(n_docs=4, capacity=256)


def test_warmed_soak_scores_zero_retraces():
    from ytpu.serving import SoakDriver

    prev_enabled = phases.enabled
    phases.enable()
    try:
        SoakDriver(_fresh_server(), _mini_cfg(), flush_every=4).run()
        rep = SoakDriver(
            _fresh_server(), _mini_cfg(), flush_every=4, retrace_budget=0
        ).run()
    finally:
        phases.enabled = prev_enabled
    comp = rep["compile"]
    assert comp["retraces"] == 0, comp
    assert comp["within_budget"] is True, comp
    prof = rep["profile"]
    assert abs(prof["fractions_sum"] - 1.0) <= 0.05, prof


@pytest.mark.slow
def test_midrun_scan_plan_flip_is_caught_and_attributed():
    """The acceptance scenario: flipping ``YTPU_SCAN_TIER_CHEAP`` mid-run
    forces a real retrace of the batch program; the journal must name
    the ``scan_plan`` axis (the changed knob), and a zero budget must
    score the run out of budget.

    Slow tier: the forced retrace pays a real ~15s XLA recompile of the
    flipped-plan batch program on CPU. The fast unit tests above pin the
    same counting/attribution mechanics, and `bench.py --dry-run`'s
    observatory storm leg exercises this exact end-to-end path."""
    from ytpu.models.batch_doc import scan_tier_plan
    from ytpu.serving import SoakDriver

    prev_enabled = phases.enabled
    prev_env = os.environ.get("YTPU_SCAN_TIER_CHEAP")
    phases.enable()

    def flip():
        cur = scan_tier_plan()[0]
        os.environ["YTPU_SCAN_TIER_CHEAP"] = str(4 if cur != 4 else 8)

    try:
        # warm every program this scenario dispatches
        SoakDriver(_fresh_server(), _mini_cfg(), flush_every=4).run()
        rep = SoakDriver(
            _fresh_server(),
            _mini_cfg(),
            flush_every=4,
            retrace_budget=0,
            probe_at=0.5,
            probe=flip,
        ).run()
    finally:
        phases.enabled = prev_enabled
        if prev_env is None:
            os.environ.pop("YTPU_SCAN_TIER_CHEAP", None)
        else:
            os.environ["YTPU_SCAN_TIER_CHEAP"] = prev_env
    comp = rep["compile"]
    assert comp["retraces"] >= 1, comp
    assert comp["within_budget"] is False, comp
    axes = {
        d["axis"] for ev in comp["journal"] for d in (ev.get("delta") or [])
    }
    assert "scan_plan" in axes, comp["journal"]


# ---------------------------------------------------------------------------
# label-cardinality guard: a tenant flood folds into `other`, bounded
# ---------------------------------------------------------------------------


def test_cardinality_guard_folds_tenant_flood(monkeypatch):
    monkeypatch.setenv("YTPU_METRICS_MAX_LABELSETS", "64")
    # a private registry keeps the synthetic family out of the global
    # exposition (the obs lint asserts every GLOBAL family is
    # documented); the drop counter is global by design — the guard
    # reports into the process registry whichever registry overflowed
    reg = MetricsRegistry()
    fam = reg.counter("obs_test.tenant_flood", labelnames=("tenant",))
    dropped = metrics.counter("metrics.cardinality_dropped")
    before = dropped.value
    for i in range(10_000):
        fam.labels(f"tenant{i}").inc()
    # 64 real children + the reserved overflow child, nothing more
    assert len(fam._children) <= 65, len(fam._children)
    other = fam.labels("other")
    assert other.value == 10_000 - 64, other.value
    assert dropped.value - before == 10_000 - 64
    # no counts were lost: the family total is exact
    total = sum(c.value for c in fam._children.values())
    assert total == 10_000
    # the fold is sticky and the guard re-reads the env per miss
    fam.labels("tenant_one_more").inc()
    assert fam.labels("other").value == 10_000 - 64 + 1


def test_cardinality_guard_exports_other_label():
    reg = MetricsRegistry()
    fam = reg.counter("obs_test.tiny_family", labelnames=("who",))
    with pytest.MonkeyPatch.context() as mp:
        mp.setenv("YTPU_METRICS_MAX_LABELSETS", "1")
        fam.labels("a").inc()
        fam.labels("b").inc()  # folds: family already at the cap
    text = reg.prometheus_text()
    assert 'obs_test_tiny_family_total{who="a"} 1' in text
    assert 'obs_test_tiny_family_total{who="other"} 1' in text
