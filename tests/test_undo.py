"""UndoManager semantics (model: reference undo.rs tests + ywasm undo tests)."""

import pytest

from ytpu.core import Doc
from ytpu.types import MapPrelim
from ytpu.undo import UndoManager, UndoOptions


def make(doc_kw=None, **opts):
    doc = Doc(client_id=1, **(doc_kw or {}))
    txt = doc.get_text("t")
    mgr = UndoManager(doc, txt, UndoOptions(capture_timeout_ms=0, **opts))
    return doc, txt, mgr


def test_undo_redo_text_insert():
    doc, txt, mgr = make()
    with doc.transact() as txn:
        txt.insert(txn, 0, "hello")
    with doc.transact() as txn:
        txt.insert(txn, 5, " world")
    assert txt.get_string() == "hello world"
    assert mgr.undo()
    assert txt.get_string() == "hello"
    assert mgr.undo()
    assert txt.get_string() == ""
    assert not mgr.can_undo()
    assert mgr.redo()
    assert txt.get_string() == "hello"
    assert mgr.redo()
    assert txt.get_string() == "hello world"
    assert not mgr.can_redo()


def test_undo_delete_restores_text():
    doc, txt, mgr = make()
    with doc.transact() as txn:
        txt.insert(txn, 0, "keep me safe")
    mgr.reset()
    with doc.transact() as txn:
        txt.remove_range(txn, 4, 3)  # removes " me"
    assert txt.get_string() == "keep safe"
    assert mgr.undo()
    assert txt.get_string() == "keep me safe"


def test_capture_timeout_groups_changes():
    t = [1.0]
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    mgr = UndoManager(
        doc, txt, UndoOptions(capture_timeout_ms=500, timestamp=lambda: t[0])
    )
    with doc.transact() as txn:
        txt.insert(txn, 0, "a")
    t[0] += 100  # within capture window: extends the same stack item
    with doc.transact() as txn:
        txt.insert(txn, 1, "b")
    t[0] += 1000  # outside: new item
    with doc.transact() as txn:
        txt.insert(txn, 2, "c")
    assert len(mgr.undo_stack) == 2
    assert mgr.undo()
    assert txt.get_string() == "ab"
    assert mgr.undo()
    assert txt.get_string() == ""


def test_tracked_origins_filter():
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    mgr = UndoManager(
        doc, txt, UndoOptions(capture_timeout_ms=0, tracked_origins={"editor"})
    )
    with doc.transact(origin="editor") as txn:
        txt.insert(txn, 0, "tracked")
    with doc.transact(origin="sync") as txn:
        txt.insert(txn, 7, " untracked")
    assert len(mgr.undo_stack) == 1
    assert mgr.undo()
    # only the tracked edit was undone
    assert txt.get_string() == " untracked"


def test_remote_changes_not_undone():
    doc, txt, mgr = make()
    with doc.transact() as txn:
        txt.insert(txn, 0, "local")
    remote = Doc(client_id=2)
    rt = remote.get_text("t")
    with remote.transact() as txn:
        rt.insert(txn, 0, "remote-")
    # remote update arrives with a non-tracked origin (as providers do)
    doc.apply_update_v1(
        remote.encode_state_as_update_v1(doc.state_vector()), origin="provider"
    )
    assert txt.get_string() == "localremote-"
    assert mgr.undo()
    assert txt.get_string() == "remote-"


def test_map_undo():
    doc = Doc(client_id=1)
    m = doc.get_map("m")
    mgr = UndoManager(doc, m, UndoOptions(capture_timeout_ms=0))
    with doc.transact() as txn:
        m.insert(txn, "k", "v1")
    with doc.transact() as txn:
        m.insert(txn, "k", "v2")
    assert m.get("k") == "v2"
    assert mgr.undo()
    assert m.get("k") == "v1"
    assert mgr.undo()
    assert m.get("k") is None
    assert mgr.redo()
    assert m.get("k") == "v1"
    assert mgr.redo()
    assert m.get("k") == "v2"


def test_scope_filtering():
    doc = Doc(client_id=1)
    t1 = doc.get_text("tracked")
    t2 = doc.get_text("other")
    mgr = UndoManager(doc, t1, UndoOptions(capture_timeout_ms=0))
    with doc.transact() as txn:
        t2.insert(txn, 0, "outside scope")
    assert not mgr.can_undo()
    with doc.transact() as txn:
        t1.insert(txn, 0, "in scope")
    assert mgr.can_undo()
    mgr.undo()
    assert t1.get_string() == ""
    assert t2.get_string() == "outside scope"


def test_undo_survives_sync_roundtrip():
    doc, txt, mgr = make()
    with doc.transact() as txn:
        txt.insert(txn, 0, "abc")
    mgr.undo()
    assert txt.get_string() == ""
    # a peer that has seen both the insert and the undo converges to empty
    peer = Doc(client_id=7)
    peer.apply_update_v1(doc.encode_state_as_update_v1())
    assert peer.get_text("t").get_string() == ""
    mgr.redo()
    peer.apply_update_v1(doc.encode_state_as_update_v1(peer.state_vector()))
    assert peer.get_text("t").get_string() == "abc"


def test_double_undo_then_insert():
    """Scenario parity: undo.rs double_undo — two undos of two grouped-out
    inserts, then a fresh insert lands at the right position."""
    doc = Doc(client_id=1)
    txt = doc.get_text("test")
    with doc.transact() as txn:
        txt.insert(txn, 0, "1221")
    mgr = UndoManager(doc, txt)
    with doc.transact() as txn:
        txt.insert(txn, 2, "3")
    with doc.transact() as txn:
        txt.insert(txn, 3, "3")
    mgr.undo()
    mgr.undo()
    with doc.transact() as txn:
        txt.insert(txn, 2, "3")
    assert txt.get_string() == "12321"


def test_consecutive_undo_redo_ladder():
    """Scenario parity: undo.rs consecutive_redo_bug (yjs#355) — reset()
    boundaries create a ladder of stack items; undo steps down through
    every state to null, redo climbs all the way back."""
    doc = Doc(client_id=1)
    root = doc.get_map("root")
    mgr = UndoManager(doc, root)

    with doc.transact() as txn:
        root.insert(txn, "a", MapPrelim({"x": 0, "y": 0}))
    point = root.get("a")
    mgr.reset()
    for v in (100, 200, 300):
        with doc.transact() as txn:
            point.insert(txn, "x", v)
            point.insert(txn, "y", v)
        mgr.reset()
    assert point.to_json() == {"x": 300, "y": 300}

    for v in (200, 100, 0):
        mgr.undo()
        assert root.get("a").to_json() == {"x": v, "y": v}, v
    mgr.undo()
    assert root.get("a") is None
    for v in (0, 100, 200, 300):
        mgr.redo()
        assert root.get("a").to_json() == {"x": v, "y": v}, v


def test_undo_delete_restores_text_format():
    """Scenario parity: undo.rs undo_delete_text_format (yjs#392) — undoing
    a format-removal restores the bold run on both peers."""
    d1 = Doc(client_id=1)
    t1 = d1.get_text("test")
    with d1.transact() as txn:
        t1.insert(txn, 0, "Attack ships on fire off the shoulder of Orion.")
    d2 = Doc(client_id=2)
    d2.apply_update_v1(d1.encode_state_as_update_v1())

    mgr = UndoManager(d1, t1)
    with d1.transact() as txn:
        t1.format(txn, 13, 7, {"bold": True})
    mgr.reset()
    d2.apply_update_v1(d1.encode_state_as_update_v1(d2.state_vector()))

    with d1.transact() as txn:
        t1.format(txn, 16, 4, {"bold": None})
    mgr.reset()
    d2.apply_update_v1(d1.encode_state_as_update_v1(d2.state_vector()))

    mgr.undo()
    d2.apply_update_v1(d1.encode_state_as_update_v1(d2.state_vector()))

    def runs(doc):
        return [
            (r.insert, r.attributes)
            for r in doc.get_text("test").diff()
        ]

    expect = [
        ("Attack ships ", None),
        ("on fire", {"bold": True}),
        (" off the shoulder of Orion.", None),
    ]
    assert runs(d1) == expect, runs(d1)
    assert runs(d2) == expect, runs(d2)


def test_special_deletion_case_xml():
    """Scenario parity: undo.rs special_deletion_case (yjs#447) — an
    origin-scoped txn edits an attribute AND deletes the node; undo must
    resurrect the node with its ORIGINAL attributes."""
    from ytpu.types import XmlElementPrelim

    doc = Doc(client_id=1)
    f = doc.get_xml_fragment("test")
    mgr = UndoManager(doc, f, UndoOptions(tracked_origins={"undoable"}))
    with doc.transact() as txn:
        f.insert(txn, 0, XmlElementPrelim("test"))
        e = f.get(0)
        e.insert_attribute(txn, "a", "1")
        e.insert_attribute(txn, "b", "2")
    s = f.get_string()
    assert s in ('<test a="1" b="2"></test>', '<test b="2" a="1"></test>')
    with doc.transact(origin="undoable") as txn:
        e = f.get(0)
        e.insert_attribute(txn, "b", "3")
        f.remove_range(txn, 0, 1)
    assert f.get_string() == ""
    mgr.undo()
    s = f.get_string()
    assert s in ('<test a="1" b="2"></test>', '<test b="2" a="1"></test>'), s
