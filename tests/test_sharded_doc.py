"""Sequence-parallel CRDT (`ytpu.parallel.sharded_doc`) vs the host oracle.

The done-bar from SURVEY §5.7 / VERDICT r2 #3: real *wire updates* (not
position ops) integrate on a doc whose block columns — ids, origins,
tombstones — are sharded across the sp axis, and the result is
byte-identical to the host oracle (a `Doc(skip_gc=True)` replica).
"""

import os
import random

import numpy as np
import pytest

from ytpu.core import Doc
from ytpu.parallel.sharded_doc import ShardedDoc


def capture(doc):
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    return log


def random_edit(txn, txt, rng, length):
    if length > 10 and rng.random() < 0.3:
        pos = rng.randint(0, length - 3)
        n = rng.randint(1, 3)
        txt.remove_range(txn, pos, n)
        return length - n
    w = "".join(rng.choice("abcdefgh ") for _ in range(rng.randint(1, 5)))
    txt.insert(txn, rng.randint(0, length), w)
    return length + len(w)


def sequential_log(n_ops, seed=3):
    src = Doc(client_id=1)
    log = capture(src)
    t = src.get_text("text")
    rng = random.Random(seed)
    length = 0
    for _ in range(n_ops):
        with src.transact() as txn:
            length = random_edit(txn, t, rng, length)
    return log, t.get_string()


def oracle_replay(updates):
    doc = Doc(client_id=99, skip_gc=True)
    for u in updates:
        doc.apply_update_v1(u)
    return doc


def test_sequential_replay_byte_identical():
    """8-shard wire replay with mid-stream rebalances == oracle, byte-exact."""
    log, expect = sequential_log(300)
    sd = ShardedDoc(n_shards=8, capacity=512)
    for i, p in enumerate(log):
        sd.apply_update_v1(p)
        if i in (60, 180):
            sd.rebalance()
    assert sd.get_string() == expect
    oracle = oracle_replay(log)
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()
    lens = sd.shard_lengths()
    assert int(lens.sum()) == len(expect)
    # after the last rebalance + tail ops, content is genuinely distributed
    assert np.count_nonzero(lens) >= 4


def test_find_position_prefix_sum():
    log, expect = sequential_log(120, seed=11)
    sd = ShardedDoc(n_shards=4, capacity=512)
    for p in log:
        sd.apply_update_v1(p)
    sd.rebalance()
    lens = sd.shard_lengths()
    cum = np.concatenate([[0], np.cumsum(lens)])
    for pos in (0, 1, len(expect) // 2, len(expect) - 1):
        shard, off = sd.find_position(pos)
        assert cum[shard] + off == pos
        assert 0 <= off < max(1, lens[shard] + 1)


def _concurrent_updates():
    base = Doc(client_id=1)
    t1 = base.get_text("text")
    with base.transact() as txn:
        t1.insert(txn, 0, "abcdefghijklmnop")
    state0 = base.encode_state_as_update_v1()
    peer_a, peer_b = Doc(client_id=2), Doc(client_id=3)
    peer_a.apply_update_v1(state0)
    peer_b.apply_update_v1(state0)
    ta, tb = peer_a.get_text("text"), peer_b.get_text("text")
    with peer_a.transact() as txn:
        ta.insert(txn, 4, "AAA")  # same spot as peer_b: conflict scan
        ta.insert(txn, 19, "XX")  # tail append (boundary-open right)
    with peer_b.transact() as txn:
        tb.insert(txn, 4, "BBB")
        tb.remove_range(txn, 8, 4)  # delete spanning a shard cut
    sv = base.state_vector()
    return state0, peer_a.encode_state_as_update_v1(sv), peer_b.encode_state_as_update_v1(sv)


@pytest.mark.parametrize("order", ["ab", "ba"])
def test_concurrent_boundary_edits(order):
    """Concurrent conflict-scan + cross-cut delete + boundary-open append:
    exercise the halo/host-resolution path; both orders converge byte-exact."""
    state0, upd_a, upd_b = _concurrent_updates()
    upds = (upd_a, upd_b) if order == "ab" else (upd_b, upd_a)
    sd = ShardedDoc(n_shards=4, capacity=256)
    sd.apply_update_v1(state0)
    sd.rebalance()
    for u in upds:
        sd.apply_update_v1(u)
    oracle = oracle_replay((state0,) + upds)
    assert sd.get_string() == oracle.get_text("text").get_string()
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_multi_peer_fuzz_convergence():
    """N peers editing concurrently in rounds; the sharded doc applies the
    same update streams and stays byte-identical to the oracle replica."""
    rng = random.Random(7)
    peers = [Doc(client_id=i + 1) for i in range(4)]
    texts = [p.get_text("text") for p in peers]
    all_updates = []

    def sync_all():
        # full mesh exchange until quiescent
        for _ in range(2):
            for i, a in enumerate(peers):
                for b in peers:
                    if a is b:
                        continue
                    diff = a.encode_state_as_update_v1(b.state_vector())
                    b.apply_update_v1(diff)

    for round_ in range(6):
        for i, p in enumerate(peers):
            log = capture(p)
            with p.transact() as txn:
                length = len(texts[i].get_string())
                random_edit(txn, texts[i], rng, length)
            all_updates.extend(log)
        sync_all()

    reference = texts[0].get_string()
    assert all(t.get_string() == reference for t in texts)

    sd = ShardedDoc(n_shards=4, capacity=1024)
    for i, u in enumerate(all_updates):
        sd.apply_update_v1(u)
        if i == len(all_updates) // 2:
            sd.rebalance()
    assert sd.get_string() == reference
    oracle = oracle_replay(all_updates)
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_pending_update_stash():
    """An update arriving before its dependencies stashes and replays once
    the missing clocks land (transaction.rs:675-727 semantics)."""
    src = Doc(client_id=1)
    log = capture(src)
    t = src.get_text("text")
    for ch in "abc":
        with src.transact() as txn:
            t.insert(txn, len(t.get_string()), ch)
    sd = ShardedDoc(n_shards=2, capacity=64)
    sd.apply_update_v1(log[0])
    sd.apply_update_v1(log[2])  # depends on log[1]'s clock: must stash
    assert sd.get_string() == "a"
    assert sd.pending
    sd.apply_update_v1(log[1])
    assert sd.get_string() == "abc"
    assert not sd.pending


def test_delete_spanning_many_shards():
    log, expect = sequential_log(80, seed=23)
    src_final = oracle_replay(log)
    sd = ShardedDoc(n_shards=8, capacity=512)
    for p in log:
        sd.apply_update_v1(p)
    sd.rebalance()
    # one more editor deletes a huge center range spanning several shards
    peer = Doc(client_id=50)
    peer.apply_update_v1(src_final.encode_state_as_update_v1())
    tp = peer.get_text("text")
    plog = capture(peer)
    with peer.transact() as txn:
        tp.remove_range(txn, 2, len(expect) - 4)
    sd.apply_update_v1(plog[0])
    oracle = oracle_replay(log + plog)
    assert sd.get_string() == oracle.get_text("text").get_string()
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_sp_mesh_execution():
    """The same replay with the shard axis laid out over an 8-device mesh:
    results identical (the SPMD path of SURVEY §5.7)."""
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    if devs.size < 8:
        pytest.skip("needs 8 virtual devices")
    log, expect = sequential_log(150, seed=31)
    sd = ShardedDoc(n_shards=8, capacity=512)
    sd.apply_update_v1(log[0])
    sd.rebalance()
    mesh = Mesh(devs, ("sp",))
    sd.place_on_mesh(mesh)
    for p in log[1:]:
        sd.apply_update_v1(p)
    assert sd.get_string() == expect
    oracle = oracle_replay(log)
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_midblock_origin_boundary_resolution():
    """A peer that synced only a prefix appends with a mid-block origin
    while later shards hold content: the host resolver must scan at
    fragment granularity (virtual repair splits), not whole blocks."""
    src = Doc(client_id=1)
    log = capture(src)
    t = src.get_text("text")
    with src.transact() as txn:
        t.insert(txn, 0, "abcde")  # clocks 0-4
    with src.transact() as txn:
        t.insert(txn, 5, "fghijklmnop")  # clocks 5-15

    peer = Doc(client_id=2)
    peer.apply_update_v1(log[0])  # prefix only: knows clocks 0-4
    tp = peer.get_text("text")
    plog = capture(peer)
    with peer.transact() as txn:
        tp.insert(txn, 5, "ZZ")  # origin (1,4), open right

    sd = ShardedDoc(n_shards=4, capacity=256)
    for p in log:
        sd.apply_update_v1(p)
    sd.rebalance()  # cuts at 4/8/12: origin (1,4) is mid-row in shard 1
    sd.apply_update_v1(plog[0])

    oracle = oracle_replay(log + plog)
    assert sd.get_string() == oracle.get_text("text").get_string()
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


B4_TRACE = "/root/reference/assets/bench-input/b4-editing-trace.bin"


@pytest.mark.skipif(not os.path.exists(B4_TRACE), reason="trace asset absent")
def test_b4_prefix_replay():
    """A real B4 editing-trace prefix as wire updates over 8 shards."""
    n_ops = 4000 if os.environ.get("YTPU_RUN_SLOW") else 800
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "b4bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    ops = bench.load_b4_ops(n_ops)
    log, expect = bench.build_updates(ops)
    sd = ShardedDoc(n_shards=8, capacity=4096, max_rows_per_step=256)
    for i, p in enumerate(log):
        sd.apply_update_v1(p)
        if i % 1500 == 1000:
            sd.rebalance()
    assert sd.get_string() == expect
    oracle = oracle_replay(log)
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()
    lens = sd.shard_lengths()
    assert int(lens.sum()) == len(expect)


def test_text_plus_map_doc_byte_identical():
    """VERDICT r3 #5: a text+map doc (the reference's normal mixed shape)
    replays on 8 shards byte-identically — map keys live as per-key LWW
    chains on their key shard, the text stays sequence-partitioned, and a
    mid-stream rebalance preserves both."""
    src = Doc(client_id=1)
    log = capture(src)
    t = src.get_text("text")
    m = src.get_map("text")  # same root: text+map components of ONE branch
    rng = random.Random(7)
    length = 0
    for i in range(120):
        with src.transact() as txn:
            if i % 3 == 0:
                m.insert(txn, f"k{rng.randint(0, 9)}", rng.randint(0, 999))
            else:
                length = random_edit(txn, t, rng, length)

    sd = ShardedDoc(n_shards=8, capacity=512)
    for i, p in enumerate(log):
        sd.apply_update_v1(p)
        if i == 60:
            sd.rebalance()
    assert sd.get_string() == t.get_string()
    assert sd.get_map() == m.to_json()
    oracle = oracle_replay(log)
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_concurrent_map_writers_lww_byte_identical():
    """Concurrent writers on the same keys: the sharded chains resolve the
    same winners as the oracle and the encode stays byte-exact."""
    a, b = Doc(client_id=5), Doc(client_id=9)
    log_a, log_b = capture(a), capture(b)
    ma, mb = a.get_map("m"), b.get_map("m")
    ta, tb = a.get_text("m"), b.get_text("m")
    with a.transact() as txn:
        ma.insert(txn, "color", "red")
        ta.insert(txn, 0, "alpha")
    with b.transact() as txn:
        mb.insert(txn, "color", "blue")
        mb.insert(txn, "size", 4)
        tb.insert(txn, 0, "beta")
    # one-way sync: a sees b's writes (concurrent chains); b stays behind
    for p in list(log_b):
        a.apply_update_v1(p)
    with a.transact() as txn:
        ma.insert(txn, "color", "green")  # new winner over the merged chain
        ma.remove(txn, "size")

    sd = ShardedDoc(n_shards=4, capacity=256)
    for p in log_a + log_b:
        sd.apply_update_v1(p)
    oracle = oracle_replay(log_a + log_b)
    assert sd.get_map() == oracle.get_map("m").to_json()
    assert sd.get_string() == oracle.get_text("m").get_string()
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_map_chain_fuzz_byte_identical():
    """Randomized multi-writer map+text fuzz: 3 peers, random sync points,
    final encode byte-equal to the oracle."""
    rng = random.Random(23)
    peers = [Doc(client_id=10 + i) for i in range(3)]
    logs = [capture(d) for d in peers]
    length = [0, 0, 0]
    for step in range(60):
        i = rng.randrange(3)
        d = peers[i]
        with d.transact() as txn:
            r = rng.random()
            if r < 0.4:
                d.get_map("doc").insert(
                    txn, f"k{rng.randint(0, 4)}", rng.randint(0, 99)
                )
            elif r < 0.5 and len(list(d.get_map("doc").keys())):
                key = next(iter(d.get_map("doc").keys()))
                d.get_map("doc").remove(txn, key)
            else:
                length[i] = random_edit(txn, d.get_text("doc"), rng, length[i])
        if rng.random() < 0.3:
            j = rng.randrange(3)
            if j != i:
                peers[j].apply_update_v1(
                    d.encode_state_as_update_v1(peers[j].state_vector())
                )
    all_updates = [p for log in logs for p in log]
    sd = ShardedDoc(n_shards=8, capacity=512)
    oracle = oracle_replay(all_updates)
    for p in all_updates:
        sd.apply_update_v1(p)
    assert sd.get_string() == oracle.get_text("doc").get_string()
    assert sd.get_map() == oracle.get_map("doc").to_json()
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_nested_xml_tree_byte_exact_8_shards():
    """Round 5 (VERDICT r4 #6): a sharded XML-tree replay — elements,
    attributes (nested LWW chains), nested text edits, two concurrent
    clients — is byte-exact vs the skip_gc oracle on 8 shards. Nested
    branches are shard-affine with their ContentType row; the primary
    root's children distribute across segments."""
    from ytpu.types import XmlElementPrelim, XmlTextPrelim

    rng = random.Random(11)
    a, b = Doc(client_id=1, skip_gc=True), Doc(client_id=2, skip_gc=True)
    relay = Doc(client_id=0xFFFF, skip_gc=True)
    log = capture(relay)
    fa, fb = a.get_xml_fragment("x"), b.get_xml_fragment("x")
    with a.transact() as txn:
        fa.insert(txn, 0, XmlElementPrelim("doc"))
        fa.insert(txn, 1, XmlTextPrelim("seed"))
    relay.apply_update_v1(a.encode_state_as_update_v1(relay.state_vector()))
    b.apply_update_v1(a.encode_state_as_update_v1(b.state_vector()))
    for step in range(50):
        doc, frag = (a, fa) if rng.random() < 0.5 else (b, fb)
        with doc.transact() as txn:
            r = rng.random()
            kids = list(frag.children())
            if r < 0.3:
                frag.insert(
                    txn,
                    rng.randrange(len(kids) + 1),
                    XmlElementPrelim(f"e{step}", attributes={"n": str(step)}),
                )
            elif r < 0.6 and kids:
                el = kids[rng.randrange(len(kids))]
                if hasattr(el, "insert_attribute"):
                    el.insert_attribute(txn, f"k{step % 5}", str(step))
            else:
                tx = [k for k in kids if type(k).__name__ == "XmlText"]
                if tx:
                    t = tx[rng.randrange(len(tx))]
                    n = len(t)
                    if n > 3 and rng.random() < 0.3:
                        t.remove_range(txn, rng.randrange(n - 2), 2)
                    else:
                        t.insert(txn, rng.randrange(n + 1), f"w{step} ")
        relay.apply_update_v1(doc.encode_state_as_update_v1(relay.state_vector()))
        other = b if doc is a else a
        other.apply_update_v1(doc.encode_state_as_update_v1(other.state_vector()))

    oracle = Doc(client_id=0xBEEF, skip_gc=True)
    sd = ShardedDoc(n_shards=8, capacity=2048, root_name="x")
    for p in log:
        sd.apply_update_v1(p)
        oracle.apply_update_v1(p)
    sd.flush()
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_multi_root_byte_exact_8_shards():
    """Round 5: secondary roots (a text root + a map root next to the
    primary fragment) anchor through BLOCK_ROOT_ANCHOR rows and re-encode
    byte-exactly."""
    from ytpu.types import XmlElementPrelim

    d = Doc(client_id=1, skip_gc=True)
    log = capture(d)
    frag = d.get_xml_fragment("x")
    m = d.get_map("meta")
    t = d.get_text("title")
    with d.transact() as txn:
        frag.insert(txn, 0, XmlElementPrelim("div", attributes={"id": "a"}))
    with d.transact() as txn:
        m.insert(txn, "version", 3)
        t.insert(txn, 0, "hello")
    with d.transact() as txn:
        t.insert(txn, 5, " world")
        m.insert(txn, "version", 4)
    with d.transact() as txn:
        t.remove_range(txn, 0, 3)

    oracle = Doc(client_id=0xBEEF, skip_gc=True)
    sd = ShardedDoc(n_shards=8, capacity=1024, root_name="x")
    for p in log:
        sd.apply_update_v1(p)
        oracle.apply_update_v1(p)
    sd.flush()
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_shard_local_moves_byte_exact():
    """Round 5: move carriers integrate when their range lives whole on
    the move's shard (always true while the doc sits in one segment, and
    always true inside shard-affine branches): random move/insert/delete
    mixes replay byte-exactly vs the skip_gc oracle."""
    for seed in (3, 7):
        rng = random.Random(seed)
        d = Doc(client_id=1, skip_gc=True)
        log = capture(d)
        arr = d.get_array("a")
        with d.transact() as txn:
            arr.insert_range(txn, 0, list(range(8)))
        for step in range(25):
            with d.transact() as txn:
                n = len(arr)
                r = rng.random()
                if r < 0.35 and n > 2:
                    s = rng.randrange(n)
                    t = rng.randrange(n)
                    if t not in (s, s + 1):
                        arr.move_to(txn, s, t)
                elif r < 0.5 and n > 4:
                    a0 = rng.randrange(n - 2)
                    a1 = a0 + rng.randrange(1, min(3, n - a0 - 1))
                    t = rng.choice(
                        [x for x in range(n) if x < a0 or x > a1 + 1] or [0]
                    )
                    arr.move_range_to(txn, a0, a1, t)
                elif r < 0.7 and n > 3:
                    arr.remove_range(txn, rng.randrange(n - 1), 1)
                else:
                    arr.insert(txn, rng.randrange(n + 1), 100 + step)
        sd = ShardedDoc(n_shards=8, capacity=1024, root_name="a")
        oracle = Doc(client_id=9, skip_gc=True)
        for p in log:
            oracle.apply_update_v1(p)
            sd.apply_update_v1(p)
        sd.flush()
        assert sd.get_values() == oracle.get_array("a").to_json(), seed
        assert (
            sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()
        ), seed


def test_cross_segment_move_renders_and_encodes():
    """Round 5 (second session): a move whose range bound lives on a
    different shard than the move row integrates via CLAIM MIRRORS
    (localized bounds per shard, no wire identity) instead of raising;
    rendering assembles the moved content across segments and the wire
    encode stays byte-exact vs the skip_gc oracle."""
    arr_doc = Doc(client_id=2, skip_gc=True)
    sd = ShardedDoc(n_shards=4, capacity=512, root_name="a")
    log2 = capture(arr_doc)
    arr = arr_doc.get_array("a")
    with arr_doc.transact() as txn:
        arr.insert_range(txn, 0, list(range(12)))
    sd.apply_update_v1(log2[0])
    sd.rebalance()  # spread the segment across shards
    with arr_doc.transact() as txn:
        arr.move_to(txn, 0, 10)  # range bound and destination far apart
    sd.apply_update_v1(log2[1])
    sd.flush()
    oracle = Doc(client_id=9, skip_gc=True)
    for p in log2:
        oracle.apply_update_v1(p)
    assert sd.get_values() == oracle.get_array("a").to_json()
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def _gcify(payload: bytes) -> bytes:
    """Rewrite a full-state update the way a gc-enabled yrs peer encodes
    it: deleted items become position-free GC carriers (BlockCell::GC)."""
    from collections import deque

    from ytpu.core.block import GCRange
    from ytpu.core.content import CONTENT_DELETED
    from ytpu.core.update import Update

    u = Update.decode_v1(payload)
    blocks = {}
    for cl, q in u.blocks.items():
        out = deque()
        for carr in q:
            if (
                getattr(carr, "is_item", False)
                and carr.content.kind == CONTENT_DELETED
            ):
                out.append(GCRange(carr.id, carr.len))
            else:
                out.append(carr)
        blocks[cl] = out
    return Update(blocks=blocks, delete_set=u.delete_set).encode_v1()


def test_gc_carriers_registry_and_encode():
    """Round 5 (second session): GC carriers from gc-enabled peers
    integrate (id-index registry, like BlockCell::GC — no sequence
    position) instead of raising; they advance the SV, re-emit at encode
    in per-client clock order, and land in the delete set — byte-exact
    vs a host replica that applied the same GC'd state."""
    a = Doc(client_id=1)
    t = a.get_text("t")
    with a.transact() as txn:
        t.insert(txn, 0, "hello cruel world")
    with a.transact() as txn:
        t.remove_range(txn, 5, 6)  # " cruel"
    payload = _gcify(a.encode_state_as_update_v1())

    sd = ShardedDoc(n_shards=4, capacity=256, root_name="t")
    sd.apply_update_v1(payload)
    sd.flush()
    replica = Doc(client_id=9)
    replica.apply_update_v1(payload)
    # reference-faithful: " world"'s only anchor is GC'd, so the carrier
    # DEGRADES to a GC range (update.rs unresolvable-parent rule) — the
    # oracle keeps just "hello", and so must the sharded engine
    assert replica.get_text("t").get_string() == "hello"
    assert sd.get_string() == "hello"
    assert sd._gc_ranges, "GC carriers should populate the registry"
    assert sd.encode_state_as_update_v1() == replica.encode_state_as_update_v1()


@pytest.mark.parametrize(
    "insert_at",
    [
        3,  # origin 'c' + ror 'd' both GC'd -> the carrier DEGRADES to a
        #     GC range (reference update.rs unresolvable-parent rule)
        4,  # origin 'd' GC'd, ror 'e' live -> parent via the right
        #     anchor, host boundary scan places the row
        1,  # origin 'a' live, ror 'b' GC'd -> left-only integration,
        #     scan to the tail (reference right=None behavior)
    ],
)
def test_item_anchored_into_gcd_region(insert_at):
    """A stale peer's insert whose anchors were since GC'd: parity vs the
    host oracle applying the same updates in the same order (the oracle
    IS the ported reference semantics, incl. the degrade-to-GC rule)."""
    a = Doc(client_id=1)
    b = Doc(client_id=2)
    ta = a.get_text("t")
    with a.transact() as txn:
        ta.insert(txn, 0, "abcdef")
    pre_gc = a.encode_state_as_update_v1()
    b.apply_update_v1(pre_gc)
    tb = b.get_text("t")
    with b.transact() as txn:
        tb.insert(txn, insert_at, "XY")
    b_update = b.encode_state_as_update_v1(a.state_vector())
    with a.transact() as txn:
        ta.remove_range(txn, 1, 3)  # "bcd"
    gc_state = _gcify(a.encode_state_as_update_v1())

    sd = ShardedDoc(n_shards=4, capacity=256, root_name="t")
    sd.apply_update_v1(gc_state)
    sd.apply_update_v1(b_update)
    sd.flush()
    oracle = Doc(client_id=9)
    oracle.apply_update_v1(gc_state)
    oracle.apply_update_v1(b_update)
    assert sd.get_string() == oracle.get_text("t").get_string(), insert_at
    assert (
        sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()
    ), insert_at


def test_rebalance_with_live_moves():
    """Round 5 (second session): rebalance no longer refuses live moves —
    claim mirrors drop with the old layout and every live move re-plans
    its localized bounds + mirrors against the fresh cuts, followed by a
    full ownership recompute. Byte-exact + value parity vs the oracle
    across two re-cuts with moves before, between, and after."""
    rng = random.Random(31)
    d = Doc(client_id=1, skip_gc=True)
    log = capture(d)
    arr = d.get_array("a")
    with d.transact() as txn:
        arr.insert_range(txn, 0, list(range(14)))
    sd = ShardedDoc(n_shards=4, capacity=1024, root_name="a")
    sd.apply_update_v1(log[0])
    sd.rebalance()

    def random_op(step):
        with d.transact() as txn:
            n = len(arr)
            r = rng.random()
            if r < 0.45 and n > 2:
                s = rng.randrange(n)
                t = rng.randrange(n)
                if t not in (s, s + 1):
                    arr.move_to(txn, s, t)
            elif r < 0.6 and n > 5:
                a0 = rng.randrange(n - 3)
                a1 = a0 + rng.randrange(1, min(3, n - a0 - 1))
                t = rng.choice(
                    [x for x in range(n) if x < a0 or x > a1 + 1] or [0]
                )
                arr.move_range_to(txn, a0, a1, t)
            else:
                arr.insert(txn, rng.randrange(n + 1), 200 + step)
        sd.apply_update_v1(log[-1])

    for step in range(6):
        random_op(step)
    sd.rebalance()  # live moves present: re-plan + recompute
    for step in range(6, 12):
        random_op(step)
    sd.rebalance()
    sd.flush()
    oracle = Doc(client_id=9, skip_gc=True)
    for p in log:
        oracle.apply_update_v1(p)
    assert sd.get_values() == oracle.get_array("a").to_json()
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_gc_carrier_through_pending_stash():
    """A GC carrier arriving BEFORE the clocks below it (out-of-order
    delivery) stashes in pending and must dispatch through the GC
    registry on retry, not crash in _route_row (code-review r5)."""
    a = Doc(client_id=1)
    t = a.get_text("t")
    log = capture(a)
    with a.transact() as txn:
        t.insert(txn, 0, "base")
    with a.transact() as txn:
        t.insert(txn, 4, "-tail")
    with a.transact() as txn:
        t.remove_range(txn, 4, 5)  # "-tail" -> deleted
    full = _gcify(a.encode_state_as_update_v1())
    # deliver the LATER update (containing the GC range over "-tail")
    # first: its carriers stash; then the base fills the gap
    from collections import deque as _dq

    from ytpu.core.update import Update

    sd = ShardedDoc(n_shards=2, capacity=128, root_name="t")
    u = Update.decode_v1(full)

    later = {
        cl: _dq(c for c in q if c.id.clock >= 4) for cl, q in u.blocks.items()
    }
    earlier = {
        cl: _dq(c for c in q if c.id.clock < 4) for cl, q in u.blocks.items()
    }
    sd.apply_update(Update(blocks=later, delete_set=u.delete_set))
    assert sd.pending  # stashed on the clock gap
    sd.apply_update(Update(blocks=earlier))
    sd.flush()
    replica = Doc(client_id=9)
    replica.apply_update_v1(full)
    assert sd.get_string() == replica.get_text("t").get_string() == "base"
    assert sd.encode_state_as_update_v1() == replica.encode_state_as_update_v1()


def test_nested_branch_move_beside_multishard_root():
    """A move INSIDE a shard-affine nested branch while the primary root
    spans 4 segments: branch-scoped bounds mean the BRANCH head/tail, so
    no claim mirrors may be planted on the root segments (the pre-r5
    guard raised here; the mirror planner must treat nested moves as
    local). Wire encode stays byte-exact vs the skip_gc oracle."""
    from ytpu.types.shared import ArrayPrelim

    d = Doc(client_id=3, skip_gc=True)
    log = capture(d)
    arr = d.get_array("a")
    with d.transact() as txn:
        arr.insert_range(txn, 0, list(range(12)))
    sd = ShardedDoc(n_shards=4, capacity=512, root_name="a")
    sd.apply_update_v1(log[0])
    sd.rebalance()
    with d.transact() as txn:
        arr.insert(txn, 6, ArrayPrelim([10, 20, 30, 40]))
    with d.transact() as txn:
        nested = arr.get(6)
        nested.move_to(txn, 0, 3)  # branch-scoped walk inside the subtree
    for p in log[1:]:
        sd.apply_update_v1(p)
    sd.flush()
    oracle = Doc(client_id=9, skip_gc=True)
    for p in log:
        oracle.apply_update_v1(p)
    # no mirrors may exist for a nested move
    assert sd._move_mirrors == {}
    assert sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()


def test_cross_segment_move_fuzz_byte_exact():
    """Random move/insert/delete mixes AFTER the doc is spread over 4
    segments: claims span shard cuts (range moves included), tombstoned
    moves release mirrored claims, and both the rendered values and the
    wire encode match the skip_gc oracle at every step boundary."""
    for seed in (5, 11, 23):
        rng = random.Random(seed)
        d = Doc(client_id=1, skip_gc=True)
        log = capture(d)
        arr = d.get_array("a")
        with d.transact() as txn:
            arr.insert_range(txn, 0, list(range(16)))
        sd = ShardedDoc(n_shards=4, capacity=1024, root_name="a")
        sd.apply_update_v1(log[0])
        sd.rebalance()
        for step in range(18):
            with d.transact() as txn:
                n = len(arr)
                r = rng.random()
                if r < 0.4 and n > 2:
                    s = rng.randrange(n)
                    t = rng.randrange(n)
                    if t not in (s, s + 1):
                        arr.move_to(txn, s, t)
                elif r < 0.55 and n > 5:
                    a0 = rng.randrange(n - 3)
                    a1 = a0 + rng.randrange(1, min(3, n - a0 - 1))
                    t = rng.choice(
                        [x for x in range(n) if x < a0 or x > a1 + 1] or [0]
                    )
                    arr.move_range_to(txn, a0, a1, t)
                elif r < 0.75 and n > 3:
                    arr.remove_range(txn, rng.randrange(n - 1), 1)
                else:
                    arr.insert(txn, rng.randrange(n + 1), 100 + step)
            sd.apply_update_v1(log[-1])
        sd.flush()
        oracle = Doc(client_id=9, skip_gc=True)
        for p in log:
            oracle.apply_update_v1(p)
        assert sd.get_values() == oracle.get_array("a").to_json(), seed
        assert (
            sd.encode_state_as_update_v1() == oracle.encode_state_as_update_v1()
        ), seed


def test_end_reachable_reuses_cached_pull():
    """ADVICE r5 #5 regression: `_end_reachable` sits on the routing path
    for same-shard id-scoped move bounds — when nothing was enqueued for
    that shard since the last flush it must answer from the cached host
    pull, never dispatching a new flush (the old code forced a full
    flush + device pull per call, serializing async routing bursts)."""
    log, _ = sequential_log(40, seed=11)
    sd = ShardedDoc(n_shards=2, capacity=256)
    for p in log:
        sd.apply_update_v1(p)
    sd.flush()
    st = sd._pull()  # builds the host cache; queues are empty now

    # two doc-order-adjacent rows on shard 0: head and its right link
    head = int(np.asarray(st.start)[0])
    assert head >= 0
    nxt = int(st.blocks.right[0, head])
    assert nxt >= 0
    a = (int(st.blocks.client[0, head]), int(st.blocks.clock[0, head]))
    b = (int(st.blocks.client[0, nxt]), int(st.blocks.clock[0, nxt]))

    flushes = []
    orig_flush = sd.flush
    sd.flush = lambda: (flushes.append(1), orig_flush())[1]
    cache_before = sd._host_cache
    assert sd._end_reachable(0, a, b) is True
    assert sd._end_reachable(0, b, a) is False  # right-links are one-way
    assert not flushes, "cached path dispatched a flush"
    assert sd._host_cache is cache_before, "cached pull was rebuilt"
