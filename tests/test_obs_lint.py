"""Metric-name lint (ISSUE-11 satellite): every counter/gauge/histogram
family and every phases stage a dry-run-style exercise emits must appear
in docs/observability.md — the doc PRs 7/9/10 each had to patch by hand
after the fact. The test fails naming exactly the missing entries, so
adding a metric without documenting it is a one-line fix at review time,
not doc drift discovered two PRs later.

Also the home of the conflict-scan-width assertions (ISSUE-11 tentpole
a): the exercise below runs a real XLA-lane overlap replay, so the same
compiled (2, 256, 16) family serves the lint's phase-key collection AND
the scan-width behavior pins.

Ordering note: this file sorts between test_metrics_trace and
test_pallas_*, after test_async_overlap / test_device_server have
compiled the shared shape families — the exercise re-uses their cached
programs and adds none.
"""

import os
import re
import sys

import pytest

from ytpu.utils import metrics, phases

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DOCS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "observability.md",
)

# phase-key normalization: per-lane suffixed gauges document as the base
# name; rehearsal namespaces are bench-simulation-only by contract
_LANE_SUFFIX = re.compile(r"\.(fused|xla|host)$")


def _normalize_phase(key: str):
    if key.startswith("rehearsal"):
        return None  # documented as the rehearsal.* namespace rule
    return _LANE_SUFFIX.sub("", key)


def _exercise():
    """A compact dry-run-shaped workout touching every subsystem that
    registers series: transport + device serving + soak + admission +
    async replay + telemetry. Reuses the suite's compiled families."""
    pytest.importorskip("jax")
    import bench as _bench
    from ytpu.models.replay import FusedReplay, plan_replay
    from ytpu.serving import (
        AdmissionController,
        Scenario,
        ScenarioConfig,
        SoakDriver,
    )
    from ytpu.sync.device_server import DeviceSyncServer
    from ytpu.utils.telemetry import TelemetryServer

    phases.reset()
    phases.enable()
    try:
        # serving leg: device server + admission + soak series
        cfg = ScenarioConfig(
            n_tenants=2, n_sessions=4, events_per_session=6, seed=29
        )
        SoakDriver(
            DeviceSyncServer(n_docs=4, capacity=256),
            Scenario(cfg),
            admission=AdmissionController(max_queue=4096),
            flush_every=4,
        ).run()

        # replay leg: the async XLA-lane pipeline (scan-width surface)
        ops = []
        length = 0
        for _ in range(14):
            for i in range(20):
                ops.append(("i", length, "abcdef"[i % 6]))
                length += 1
            ops.append(("d", length - 18, 18))
            length -= 18
        log, expect = _bench.build_updates(ops)
        r = FusedReplay(
            n_docs=2,
            plan=plan_replay(log),
            capacity=256,
            max_capacity=256,
            d_block=2,
            chunk=16,
            lane="xla",
            overlap=True,
        )
        stats = r.run(log)
        assert r.get_string(0) == expect

        # telemetry leg: one scrape registers the plane's own series
        with TelemetryServer(port=0) as t:
            import urllib.request

            urllib.request.urlopen(
                f"http://127.0.0.1:{t.port}/metrics", timeout=5
            ).read()
        snap = phases.snapshot()
    finally:
        phases.disable()
        phases.reset()
    return stats, snap


def test_scan_width_histogram_rides_the_readout():
    """Tentpole (a) pins: the scan record materializes with the
    existing readout (totals + max + bucket-quantiles + the ISSUE-12
    tier/trip words), the gauges land in phases (base + lane-suffixed),
    and the bucket math is coherent."""
    from ytpu.models.batch_doc import SCAN_REC_WORDS, SCAN_WIDTH_BUCKETS

    stats, snap = _exercise()
    assert len(stats.scan_hist) == SCAN_WIDTH_BUCKETS
    total = sum(stats.scan_hist)
    assert total > 0, "no conflict scans recorded over a 294-update replay"
    assert 0 <= stats.scan_p50 <= stats.scan_p99 <= max(stats.scan_max, 1)
    # ISSUE-12 tier occupancy: every scan resolved in exactly one tier,
    # and the two-tier dispatch can never pay MORE trips than the
    # serial-equivalent loop (the accounting words ride the same record)
    assert stats.scan_tier_cheap + stats.scan_tier_wide == total, stats
    # (a scan can legitimately visit zero candidates — its entry slot is
    # already the resolved neighbor — so the trip words may both be 0)
    assert (
        0 <= stats.scan_trips_two_tier <= stats.scan_trips_serial
    ), stats
    # gauges: base keys + the per-lane twins, all in the phases snapshot
    for q in ("width_p50", "width_p99", "width_max", "tier_cheap",
              "tier_wide", "trips_serial", "trips_two_tier"):
        assert f"integrate.scan_{q}" in snap, sorted(snap)
        assert f"integrate.scan_{q}.xla" in snap
    # the record words rode the SAME readout future: their d2h bytes
    # are accounted under integrate.scan_hist, while replay.readout kept
    # its historical 12-bytes-per-readout accounting (the zero-sync
    # invariant test in test_async_overlap passes unchanged)
    assert snap["integrate.scan_hist"]["d2h_bytes"] == (
        4 * SCAN_REC_WORDS * (snap["replay.readout"]["d2h_bytes"] // 12)
    )


def test_every_emitted_metric_and_phase_name_is_documented():
    _, snap = _exercise()
    with open(DOCS) as f:
        doc = f.read()
    # metric families: every registered family name (the exercise above
    # touched every subsystem; module-level families register at import)
    missing = []
    for name in sorted(metrics._families):
        if name not in doc:
            missing.append(f"metric: {name}")
    for key in sorted(snap):
        base = _normalize_phase(key)
        if base is not None and base not in doc:
            missing.append(f"phase: {key}")
    assert not missing, (
        "undocumented observability names (add them to "
        "docs/observability.md §Metric name index):\n  "
        + "\n  ".join(missing)
    )


def _federated_exercise():
    """A dry-run-shaped federated workout with the TRACER live (ISSUE-15
    satellite f): host-only 3-replica chaos soak + canary probing, so
    every fleet span family — soak.event, canary.probe, replica.* —
    is emitted.  Returns the set of span names recorded."""
    from ytpu.serving import FederatedSoakDriver, Scenario, ScenarioConfig
    from ytpu.sync.replica import ReplicaMesh
    from ytpu.sync.server import SyncServer
    from ytpu.utils.trace import tracer

    import json as _json

    cfg = ScenarioConfig(
        n_tenants=2, n_sessions=4, events_per_session=6, seed=29
    )
    tracer.enabled = True
    try:
        tracer.clear()
        rep = FederatedSoakDriver(
            ReplicaMesh([(f"r{i}", SyncServer()) for i in range(3)]),
            Scenario(cfg),
            sync_every=4,
            anti_entropy_every=8,
            canary_every=4,
            partition_at=0.3,
            heal_at=0.5,
            failover_at=0.8,
            migrate_at=0.4,
        ).run()
        events = _json.loads(tracer.export_chrome_trace())["traceEvents"]
    finally:
        tracer.enabled = False
        tracer.clear()
    assert rep["converged"], rep
    return {e["name"] for e in events}


def test_every_emitted_span_name_is_documented():
    """Satellite (f): every span NAME a traced federated exercise emits
    must appear in docs/observability.md (the §Span name index), so a
    new span ships with its doc row or fails here by name."""
    names = _federated_exercise()
    # the chaos schedule must actually have exercised the fleet spans —
    # an empty/narrow set would vacuously pass the lint
    for expected in (
        "soak.event",
        "canary.probe",
        "replica.sync_round",
        "replica.deliver",
        "replica.anti_entropy",
        "replica.handoff",
        "replica.failover",
        "replica.migrate",
    ):
        assert expected in names, (expected, sorted(names))
    with open(DOCS) as f:
        doc = f.read()
    missing = sorted(n for n in names if n not in doc)
    assert not missing, (
        "undocumented span names (add them to docs/observability.md "
        "§Span name index):\n  " + "\n  ".join(missing)
    )


def test_window_prometheus_text_format_pin():
    """Satellite (b): `window_prometheus_text` emits a REAL Prometheus
    histogram exposition — TYPE header, cumulative `_bucket{le=...}`
    series ending in `+Inf` == `_count`, `_sum` in seconds — computed
    over the WINDOW's delta only, and an empty window still emits the
    +Inf/_sum/_count triplet."""
    import re as _re

    from ytpu.utils.metrics import Histogram
    from ytpu.utils.slo import HistogramWindow, window_prometheus_text

    # standalone Histogram (NOT registry-registered: this pin must not
    # add a family the documented-names lint would then demand)
    hist = Histogram("obs_lint.window_pin")
    hist.observe(0.5)  # pre-window sample: must NOT appear in the delta
    w = HistogramWindow(hist)
    empty = window_prometheus_text("obs_lint.window_pin", w)
    assert empty.splitlines() == [
        "# TYPE obs_lint_window_pin histogram",
        'obs_lint_window_pin_bucket{le="+Inf"} 0',
        "obs_lint_window_pin_sum 0",
        "obs_lint_window_pin_count 0",
    ]
    for s in (0.001, 0.002, 0.004, 1.0):
        hist.observe(s)
    text = window_prometheus_text("obs_lint.window_pin", w)
    lines = text.splitlines()
    assert lines[0] == "# TYPE obs_lint_window_pin histogram"
    bucket_re = _re.compile(
        r'^obs_lint_window_pin_bucket\{le="([^"]+)"\} (\d+)$'
    )
    counts = []
    uppers = []
    for ln in lines[1:-2]:
        m = bucket_re.match(ln)
        assert m, ln
        uppers.append(m.group(1))
        counts.append(int(m.group(2)))
    # cumulative, ending at +Inf == windowed count (4, not 5: the
    # pre-window sample stayed out)
    assert counts == sorted(counts)
    assert uppers[-1] == "+Inf" and counts[-1] == 4
    assert lines[-1] == "obs_lint_window_pin_count 4"
    m = _re.match(r"^obs_lint_window_pin_sum ([0-9.e+-]+)$", lines[-2])
    assert m, lines[-2]
    assert abs(float(m.group(1)) - (0.001 + 0.002 + 0.004 + 1.0)) < 0.01
    # le values are seconds, formatted like the registry's exposition
    for le in uppers[:-1]:
        float(le)
