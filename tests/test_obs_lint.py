"""Metric-name lint (ISSUE-11 satellite): every counter/gauge/histogram
family and every phases stage a dry-run-style exercise emits must appear
in docs/observability.md — the doc PRs 7/9/10 each had to patch by hand
after the fact. The test fails naming exactly the missing entries, so
adding a metric without documenting it is a one-line fix at review time,
not doc drift discovered two PRs later.

Also the home of the conflict-scan-width assertions (ISSUE-11 tentpole
a): the exercise below runs a real XLA-lane overlap replay, so the same
compiled (2, 256, 16) family serves the lint's phase-key collection AND
the scan-width behavior pins.

Ordering note: this file sorts between test_metrics_trace and
test_pallas_*, after test_async_overlap / test_device_server have
compiled the shared shape families — the exercise re-uses their cached
programs and adds none.
"""

import os
import re
import sys

import pytest

from ytpu.utils import metrics, phases

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DOCS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "observability.md",
)

# phase-key normalization: per-lane suffixed gauges document as the base
# name; rehearsal namespaces are bench-simulation-only by contract
_LANE_SUFFIX = re.compile(r"\.(fused|xla|host)$")


def _normalize_phase(key: str):
    if key.startswith("rehearsal"):
        return None  # documented as the rehearsal.* namespace rule
    return _LANE_SUFFIX.sub("", key)


def _exercise():
    """A compact dry-run-shaped workout touching every subsystem that
    registers series: transport + device serving + soak + admission +
    async replay + telemetry. Reuses the suite's compiled families."""
    pytest.importorskip("jax")
    import bench as _bench
    from ytpu.models.replay import FusedReplay, plan_replay
    from ytpu.serving import (
        AdmissionController,
        Scenario,
        ScenarioConfig,
        SoakDriver,
    )
    from ytpu.sync.device_server import DeviceSyncServer
    from ytpu.utils.telemetry import TelemetryServer

    phases.reset()
    phases.enable()
    try:
        # serving leg: device server + admission + soak series
        cfg = ScenarioConfig(
            n_tenants=2, n_sessions=4, events_per_session=6, seed=29
        )
        SoakDriver(
            DeviceSyncServer(n_docs=4, capacity=256),
            Scenario(cfg),
            admission=AdmissionController(max_queue=4096),
            flush_every=4,
        ).run()

        # replay leg: the async XLA-lane pipeline (scan-width surface)
        ops = []
        length = 0
        for _ in range(14):
            for i in range(20):
                ops.append(("i", length, "abcdef"[i % 6]))
                length += 1
            ops.append(("d", length - 18, 18))
            length -= 18
        log, expect = _bench.build_updates(ops)
        r = FusedReplay(
            n_docs=2,
            plan=plan_replay(log),
            capacity=256,
            max_capacity=256,
            d_block=2,
            chunk=16,
            lane="xla",
            overlap=True,
        )
        stats = r.run(log)
        assert r.get_string(0) == expect

        # telemetry leg: one scrape registers the plane's own series
        with TelemetryServer(port=0) as t:
            import urllib.request

            urllib.request.urlopen(
                f"http://127.0.0.1:{t.port}/metrics", timeout=5
            ).read()
        snap = phases.snapshot()
    finally:
        phases.disable()
        phases.reset()
    return stats, snap


def test_scan_width_histogram_rides_the_readout():
    """Tentpole (a) pins: the scan record materializes with the
    existing readout (totals + max + bucket-quantiles + the ISSUE-12
    tier/trip words), the gauges land in phases (base + lane-suffixed),
    and the bucket math is coherent."""
    from ytpu.models.batch_doc import SCAN_REC_WORDS, SCAN_WIDTH_BUCKETS

    stats, snap = _exercise()
    assert len(stats.scan_hist) == SCAN_WIDTH_BUCKETS
    total = sum(stats.scan_hist)
    assert total > 0, "no conflict scans recorded over a 294-update replay"
    assert 0 <= stats.scan_p50 <= stats.scan_p99 <= max(stats.scan_max, 1)
    # ISSUE-12 tier occupancy: every scan resolved in exactly one tier,
    # and the two-tier dispatch can never pay MORE trips than the
    # serial-equivalent loop (the accounting words ride the same record)
    assert stats.scan_tier_cheap + stats.scan_tier_wide == total, stats
    # (a scan can legitimately visit zero candidates — its entry slot is
    # already the resolved neighbor — so the trip words may both be 0)
    assert (
        0 <= stats.scan_trips_two_tier <= stats.scan_trips_serial
    ), stats
    # gauges: base keys + the per-lane twins, all in the phases snapshot
    for q in ("width_p50", "width_p99", "width_max", "tier_cheap",
              "tier_wide", "trips_serial", "trips_two_tier"):
        assert f"integrate.scan_{q}" in snap, sorted(snap)
        assert f"integrate.scan_{q}.xla" in snap
    # the record words rode the SAME readout future: their d2h bytes
    # are accounted under integrate.scan_hist, while replay.readout kept
    # its historical 12-bytes-per-readout accounting (the zero-sync
    # invariant test in test_async_overlap passes unchanged)
    assert snap["integrate.scan_hist"]["d2h_bytes"] == (
        4 * SCAN_REC_WORDS * (snap["replay.readout"]["d2h_bytes"] // 12)
    )


def test_every_emitted_metric_and_phase_name_is_documented():
    _, snap = _exercise()
    with open(DOCS) as f:
        doc = f.read()
    # metric families: every registered family name (the exercise above
    # touched every subsystem; module-level families register at import)
    missing = []
    for name in sorted(metrics._families):
        if name not in doc:
            missing.append(f"metric: {name}")
    for key in sorted(snap):
        base = _normalize_phase(key)
        if base is not None and base not in doc:
            missing.append(f"phase: {key}")
    assert not missing, (
        "undocumented observability names (add them to "
        "docs/observability.md §Metric name index):\n  "
        + "\n  ".join(missing)
    )
