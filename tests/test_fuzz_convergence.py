"""Seeded convergence fuzzing over the fake peer network.

Model: reference fuzz tests built on run_scenario (e.g. types/map.rs:1063-1110,
array/text equivalents) — N peers, random ops, random partial delivery,
then a convergence assertion.
"""

import random
import string

import pytest

from ytpu.testing import run_scenario
from ytpu.types import ArrayPrelim, MapPrelim, TextPrelim


def _rand_word(rng: random.Random) -> str:
    return "".join(rng.choice(string.ascii_lowercase) for _ in range(rng.randint(1, 6)))


# --- text mutators ---


def text_insert(doc, rng):
    txt = doc.get_text("text")
    pos = rng.randint(0, len(txt))
    with doc.transact() as txn:
        txt.insert(txn, pos, _rand_word(rng))


def text_delete(doc, rng):
    txt = doc.get_text("text")
    n = len(txt)
    if n == 0:
        return
    pos = rng.randint(0, n - 1)
    length = min(rng.randint(1, 5), n - pos)
    with doc.transact() as txn:
        txt.remove_range(txn, pos, length)


# --- array mutators ---


def array_insert(doc, rng):
    arr = doc.get_array("array")
    pos = rng.randint(0, len(arr))
    with doc.transact() as txn:
        arr.insert_range(txn, pos, [rng.randint(0, 100) for _ in range(rng.randint(1, 3))])


def array_delete(doc, rng):
    arr = doc.get_array("array")
    n = len(arr)
    if n == 0:
        return
    pos = rng.randint(0, n - 1)
    with doc.transact() as txn:
        arr.remove_range(txn, pos, min(rng.randint(1, 2), n - pos))


# --- map mutators ---

KEYS = ["a", "b", "c", "d", "e"]


def map_set(doc, rng):
    m = doc.get_map("map")
    with doc.transact() as txn:
        m.insert(txn, rng.choice(KEYS), _rand_word(rng))


def map_set_nested(doc, rng):
    m = doc.get_map("map")
    kind = rng.randint(0, 2)
    with doc.transact() as txn:
        if kind == 0:
            m.insert(txn, rng.choice(KEYS), MapPrelim({"n": rng.randint(0, 9)}))
        elif kind == 1:
            m.insert(txn, rng.choice(KEYS), ArrayPrelim([1, 2]))
        else:
            m.insert(txn, rng.choice(KEYS), TextPrelim(_rand_word(rng)))


def map_delete(doc, rng):
    m = doc.get_map("map")
    with doc.transact() as txn:
        m.remove(txn, rng.choice(KEYS))


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_text(seed):
    run_scenario(seed, [text_insert, text_insert, text_delete], n_peers=3, n_iterations=120)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_array(seed):
    run_scenario(seed + 100, [array_insert, array_delete], n_peers=3, n_iterations=120)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_map(seed):
    run_scenario(
        seed + 200, [map_set, map_set_nested, map_delete], n_peers=3, n_iterations=120
    )


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_mixed_5_peers(seed):
    run_scenario(
        seed + 300,
        [text_insert, text_delete, array_insert, array_delete, map_set, map_set_nested],
        n_peers=5,
        n_iterations=200,
    )


# --- move mutators ---


def array_move(doc, rng):
    arr = doc.get_array("array")
    n = len(arr)
    if n < 2:
        return
    src = rng.randint(0, n - 1)
    dst = rng.randint(0, n)
    with doc.transact() as txn:
        arr.move_to(txn, src, dst)


def xml_mutate(doc, rng):
    frag = doc.get_xml_fragment("xml")
    from ytpu.types import XmlElementPrelim, XmlTextPrelim

    with doc.transact() as txn:
        roll = rng.random()
        n = len(frag)
        if roll < 0.4 or n == 0:
            kind = rng.randint(0, 1)
            node = (
                XmlElementPrelim(rng.choice(["p", "div", "span"]))
                if kind
                else XmlTextPrelim(_rand_word(rng))
            )
            frag.insert(txn, rng.randint(0, n), node)
        elif roll < 0.7:
            frag.remove_range(txn, rng.randint(0, n - 1), 1)
        else:
            child = frag.get(rng.randint(0, n - 1))
            if child is not None and hasattr(child, "insert_attribute"):
                child.insert_attribute(txn, rng.choice("abc"), _rand_word(rng))


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_array_with_moves(seed):
    run_scenario(
        seed + 400, [array_insert, array_delete, array_move], n_peers=3, n_iterations=150
    )


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_xml(seed):
    run_scenario(seed + 500, [xml_mutate], n_peers=3, n_iterations=120)


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_everything(seed):
    run_scenario(
        seed + 600,
        [
            text_insert,
            text_delete,
            array_insert,
            array_delete,
            array_move,
            map_set,
            map_set_nested,
            map_delete,
            xml_mutate,
        ],
        n_peers=4,
        n_iterations=250,
    )
