"""Cross-server (pod-to-pod) replication (ytpu/sync/replica.py).

SURVEY §5.8: two server processes exchange SV-diff updates over the same
y-sync wire the clients speak (the reference's symmetric peer handshake,
sync/protocol.rs:8-31, applied server-to-server). Scenarios:

- 2 pods x 2 clients each, concurrent writes, all four ends byte-identical;
- pods that diverged BEFORE linking converge through the greeting's
  SV-diff exchange alone;
- a dropped broadcast is repaired by a gossip (anti-entropy) round;
- a device-authoritative pod replicating with a host pod.
"""

import asyncio

import numpy as np

from ytpu.core import Doc
from ytpu.sync.net import SyncClient, serve
from ytpu.sync.replica import Replicator
from ytpu.sync.server import SyncServer


def run(coro):
    return asyncio.run(coro)


def _full_state(doc: Doc) -> bytes:
    from ytpu.core.state_vector import StateVector

    return doc.encode_state_as_update_v1(StateVector({}))


async def _settle(replicator, clients=(), rounds=6):
    """Alternate replica pumping and client pumping until quiescent-ish."""
    for _ in range(rounds):
        await replicator.pump(timeout=0.1)
        for c in clients:
            await c.pump(max_frames=4, timeout=0.1)
        await asyncio.sleep(0.05)


def test_two_pods_two_clients_each_converge():
    async def main():
        pod_a, pod_b = SyncServer(), SyncServer()
        srv_a, port_a = await serve(pod_a)
        srv_b, port_b = await serve(pod_b)

        # pod A replicates tenant "room" with pod B
        rep = Replicator(pod_a, "127.0.0.1", port_b)
        await rep.add_tenant("room")

        c1, c2 = SyncClient(Doc(client_id=101)), SyncClient(Doc(client_id=102))
        c3, c4 = SyncClient(Doc(client_id=103)), SyncClient(Doc(client_id=104))
        await c1.connect("127.0.0.1", port_a, "room")
        await c2.connect("127.0.0.1", port_a, "room")
        await c3.connect("127.0.0.1", port_b, "room")
        await c4.connect("127.0.0.1", port_b, "room")
        clients = (c1, c2, c3, c4)
        for c in clients:
            await c.pump(max_frames=4, timeout=0.3)

        # concurrent writes on both pods
        with c1.doc.transact() as txn:
            c1.doc.get_text("t").insert(txn, 0, "from-a1 ")
        with c3.doc.transact() as txn:
            c3.doc.get_text("t").insert(txn, 0, "from-b1 ")
        await c1.flush()
        await c3.flush()
        await asyncio.sleep(0.1)
        await _settle(rep, clients)

        with c2.doc.transact() as txn:
            t = c2.doc.get_text("t")
            t.insert(txn, len(t.get_string()), "a2-tail")
        await c2.flush()
        await asyncio.sleep(0.1)
        await _settle(rep, clients)

        states = [_full_state(c.doc) for c in clients]
        texts = [c.doc.get_text("t").get_string() for c in clients]
        assert len(set(texts)) == 1, texts
        assert "a2-tail" in texts[0] and "from-b1" in texts[0]
        # byte-identical full-state encodings at all four ends + both pods
        assert len(set(states)) == 1
        assert _full_state(pod_a.doc("room")) == states[0]
        assert _full_state(pod_b.doc("room")) == states[0]

        for c in clients:
            await c.close()
        await rep.close()
        for srv in (srv_a, srv_b):
            srv.close()
            await srv.wait_closed()

    run(main())


def test_diverged_pods_converge_via_greeting_sv_diff():
    async def main():
        pod_a, pod_b = SyncServer(), SyncServer()
        # diverge BEFORE any link exists
        with pod_a.doc("room").transact() as txn:
            pod_a.doc("room").get_text("t").insert(txn, 0, "alpha ")
        with pod_b.doc("room").transact() as txn:
            pod_b.doc("room").get_text("t").insert(txn, 0, "beta ")
        srv_b, port_b = await serve(pod_b)

        rep = Replicator(pod_a, "127.0.0.1", port_b)
        link = await rep.add_tenant("room")
        # greeting: both sides sent SyncStep1; pump answers + applies diffs
        for _ in range(4):
            await link.pump(timeout=0.15)
            await asyncio.sleep(0.05)

        sa = _full_state(pod_a.doc("room"))
        sb = _full_state(pod_b.doc("room"))
        assert sa == sb
        text = pod_a.doc("room").get_text("t").get_string()
        assert "alpha" in text and "beta" in text

        await rep.close()
        srv_b.close()
        await srv_b.wait_closed()

    run(main())


def test_gossip_repairs_dropped_broadcast():
    async def main():
        pod_a, pod_b = SyncServer(), SyncServer()
        srv_b, port_b = await serve(pod_b)
        rep = Replicator(pod_a, "127.0.0.1", port_b)
        link = await rep.add_tenant("room")
        for _ in range(3):
            await link.pump(timeout=0.1)

        # a local write lands in the link session's outbox; drop it on the
        # floor (simulated packet loss) instead of flushing
        with pod_a.doc("room").transact() as txn:
            pod_a.doc("room").get_text("t").insert(txn, 0, "lost?")
        dropped = pod_a.drain(link.session)
        assert dropped, "write should have queued a broadcast frame"
        await link.pump(timeout=0.1)
        assert pod_b.doc("room").get_text("t").get_string() == ""

        # anti-entropy: B cannot know it is missing data until it hears a
        # state vector. In the pod mesh each side runs its own replicator;
        # here B's repair round is its step1 --> A answers with the SV-diff.
        # Drive it through B's own link back to A.
        srv_a, port_a = await serve(pod_a)
        rep_b = Replicator(pod_b, "127.0.0.1", port_a)
        link_b = await rep_b.add_tenant("room")
        for _ in range(4):
            await link_b.pump(timeout=0.1)
            await link.pump(timeout=0.1)
            await asyncio.sleep(0.03)
        assert pod_b.doc("room").get_text("t").get_string() == "lost?"

        # and a later gossip round keeps already-converged pods quiet
        await link_b.gossip()
        await link_b.pump(timeout=0.15)
        assert _full_state(pod_a.doc("room")) == _full_state(pod_b.doc("room"))

        await rep.close()
        await rep_b.close()
        for srv in (srv_a, srv_b):
            srv.close()
            await srv.wait_closed()

    run(main())


def test_device_authoritative_pod_replicates_with_host_pod():
    from ytpu.sync.device_server import DeviceSyncServer

    async def main():
        pod_dev = DeviceSyncServer(
            n_docs=2, capacity=512, device_authoritative=True
        )
        pod_host = SyncServer()
        srv_h, port_h = await serve(pod_host)

        # the device pod replicates toward the host pod
        rep = Replicator(pod_dev, "127.0.0.1", port_h)
        link = await rep.add_tenant("room")

        # a client of the device pod writes
        c_dev = SyncClient(Doc(client_id=201))
        session, greeting = pod_dev.connect_frames("room")
        # in-process client of the device pod: drive frames directly
        with c_dev.doc.transact() as txn:
            c_dev.doc.get_text("t").insert(txn, 0, "device-born")
        from ytpu.core.state_vector import StateVector
        from ytpu.sync.protocol import Message, SyncMessage

        upd = c_dev.doc.encode_state_as_update_v1(StateVector({}))
        pod_dev.receive_frames(
            session, Message.sync(SyncMessage.update(upd)).encode_v1()
        )
        pod_dev.flush_device()

        # replicate to the host pod, then on to a host-pod client
        for _ in range(4):
            await link.pump(timeout=0.15)
            await asyncio.sleep(0.05)
        assert (
            pod_host.doc("room").get_text("t").get_string() == "device-born"
        )

        # reverse direction: host-pod write reaches the device batch
        with pod_host.doc("room").transact() as txn:
            t = pod_host.doc("room").get_text("t")
            t.insert(txn, len(t.get_string()), " host-born")
        # host pod's broadcast lands in its serve()-side session for the
        # link; a pump collects it
        for _ in range(4):
            await link.pump(timeout=0.15)
            await asyncio.sleep(0.05)
        pod_dev.flush_device()
        assert pod_dev.device_text("room") == "device-born host-born"
        assert int(np.asarray(pod_dev.ingestor.state.error).max()) == 0

        await rep.close()
        srv_h.close()
        await srv_h.wait_closed()

    run(main())


def test_three_pod_mesh_partition_reconnect_convergence():
    """VERDICT r3 #8: an N-pod line mesh (A<->B<->C) with 8 tenants and
    concurrent writers converges within a BOUNDED number of pump rounds;
    a killed link mid-stream reconnects and re-converges through the
    symmetric SyncStep1 greeting alone (no sleeps-as-synchronization —
    rounds are counted)."""

    async def main():
        pods = [SyncServer(), SyncServer(), SyncServer()]
        ports = []
        srvs = []
        for p in pods:
            srv, port = await serve(p)
            srvs.append(srv)
            ports.append(port)
        tenants = [f"room{i}" for i in range(8)]
        rep_ab = Replicator(pods[0], "127.0.0.1", ports[1])
        rep_bc = Replicator(pods[1], "127.0.0.1", ports[2])
        for t in tenants:
            await rep_ab.add_tenant(t)
            await rep_bc.add_tenant(t)

        clients = []
        for i, t in enumerate(tenants):
            for pod_i in (i % 3, (i + 1) % 3):
                c = SyncClient(Doc(client_id=1000 + 10 * i + pod_i))
                await c.connect("127.0.0.1", ports[pod_i], t)
                clients.append((t, c))
        for _, c in clients:
            await c.pump(max_frames=4, timeout=0.1)

        marks: dict = {t: [] for t in tenants}
        for i, (t, c) in enumerate(clients):
            mark = f"w{i};"
            marks[t].append(mark)
            with c.doc.transact() as txn:
                c.doc.get_text("t").insert(txn, 0, mark)
            await c.flush()

        def converged() -> bool:
            for t in tenants:
                texts = {p.doc(t).get_text("t").get_string() for p in pods}
                if len(texts) != 1:
                    return False
                text = next(iter(texts))
                if not all(m in text for m in marks[t]):
                    return False
            return True

        rounds = 0
        while not converged() and rounds < 16:
            await rep_ab.pump(timeout=0.05)
            await rep_bc.pump(timeout=0.05)
            for _, c in clients:
                await c.pump(max_frames=4, timeout=0.05)
            rounds += 1
        assert converged(), f"mesh did not converge within {rounds} rounds"

        # --- partition: kill A<->B mid-stream, keep writing both sides ---
        await rep_ab.close()
        for i, (t, c) in enumerate(clients[:6]):
            mark = f"p{i};"
            marks[t].append(mark)
            with c.doc.transact() as txn:
                c.doc.get_text("t").insert(txn, 0, mark)
            await c.flush()
        # B<->C still converges between themselves while A drifts
        for _ in range(6):
            await rep_bc.pump(timeout=0.05)
            for _, c in clients:
                await c.pump(max_frames=4, timeout=0.05)
        assert not converged()  # A is partitioned and must be behind

        # --- reconnect: a FRESH replicator; greeting alone must repair ---
        rep_ab2 = Replicator(pods[0], "127.0.0.1", ports[1])
        for t in tenants:
            await rep_ab2.add_tenant(t)
        rounds2 = 0
        while not converged() and rounds2 < 16:
            await rep_ab2.pump(timeout=0.05)
            await rep_bc.pump(timeout=0.05)
            for _, c in clients:
                await c.pump(max_frames=4, timeout=0.05)
            rounds2 += 1
        assert converged(), f"post-partition reconvergence took >{rounds2} rounds"

        await rep_ab2.close()
        await rep_bc.close()
        for _, c in clients:
            await c.close()
        for srv in srvs:
            srv.close()
            await srv.wait_closed()

    run(main())


def test_slow_pod_link_evicted_and_resyncs():
    """Backpressure at the pod level: a replica link whose peer stalls is
    evicted as a slow consumer (outbox overflow -> ConnectionError on the
    next pump) instead of growing server memory; a fresh link resyncs the
    whole gap through the greeting SV-diff."""
    from ytpu.sync.server import Session

    async def main():
        pod_a, pod_b = SyncServer(), SyncServer()
        srv_a, port_a = await serve(pod_a)
        srv_b, port_b = await serve(pod_b)
        rep = Replicator(pod_a, "127.0.0.1", port_b)
        link = await rep.add_tenant("room")

        c1 = SyncClient(Doc(client_id=201))
        await c1.connect("127.0.0.1", port_a, "room")
        await c1.pump(max_frames=4, timeout=0.1)

        cap = Session.OUTBOX_CAP
        Session.OUTBOX_CAP = 16  # make the flood cheap
        try:
            # flood pod A while the replica link never pumps ("slow" B)
            for i in range(Session.OUTBOX_CAP + 4):
                with c1.doc.transact() as txn:
                    c1.doc.get_text("t").insert(txn, 0, f"x{i};")
                await c1.flush()
                # the server handler pushes broadcasts into the link's
                # outbox as frames arrive
                await asyncio.sleep(0)
            # let the server process the client frames without the link
            for _ in range(8):
                await c1.pump(max_frames=8, timeout=0.05)
            assert link.session.dead, "stalled replica link was not evicted"
            import pytest

            with pytest.raises(ConnectionError):
                await link.pump(timeout=0.05)
        finally:
            Session.OUTBOX_CAP = cap

        # recovery: a fresh link resyncs everything through the greeting
        rep2 = Replicator(pod_a, "127.0.0.1", port_b)
        await rep2.add_tenant("room")
        for _ in range(8):
            await rep2.pump(timeout=0.05)
            await c1.pump(max_frames=4, timeout=0.05)
        a_text = pod_a.doc("room").get_text("t").get_string()
        b_text = pod_b.doc("room").get_text("t").get_string()
        assert a_text == b_text and "x0;" in b_text

        await rep.close()  # the evicted link's TCP side must close too, or
        # the peer pod's handler outlives the test and wait_closed() hangs
        await rep2.close()
        await c1.close()
        for srv in (srv_a, srv_b):
            srv.close()
            await srv.wait_closed()

    run(main())
