"""Cross-server (pod-to-pod) replication (ytpu/sync/replica.py).

SURVEY §5.8: two server processes exchange SV-diff updates over the same
y-sync wire the clients speak (the reference's symmetric peer handshake,
sync/protocol.rs:8-31, applied server-to-server). Scenarios:

- 2 pods x 2 clients each, concurrent writes, all four ends byte-identical;
- pods that diverged BEFORE linking converge through the greeting's
  SV-diff exchange alone;
- a dropped broadcast is repaired by a gossip (anti-entropy) round;
- a device-authoritative pod replicating with a host pod.
"""

import asyncio

import numpy as np

from ytpu.core import Doc
from ytpu.sync.net import SyncClient, serve
from ytpu.sync.replica import Replicator
from ytpu.sync.server import SyncServer


def run(coro):
    return asyncio.run(coro)


def _full_state(doc: Doc) -> bytes:
    from ytpu.core.state_vector import StateVector

    return doc.encode_state_as_update_v1(StateVector({}))


async def _settle(replicator, clients=(), rounds=6):
    """Alternate replica pumping and client pumping until quiescent-ish."""
    for _ in range(rounds):
        await replicator.pump(timeout=0.1)
        for c in clients:
            await c.pump(max_frames=4, timeout=0.1)
        await asyncio.sleep(0.05)


def test_two_pods_two_clients_each_converge():
    async def main():
        pod_a, pod_b = SyncServer(), SyncServer()
        srv_a, port_a = await serve(pod_a)
        srv_b, port_b = await serve(pod_b)

        # pod A replicates tenant "room" with pod B
        rep = Replicator(pod_a, "127.0.0.1", port_b)
        await rep.add_tenant("room")

        c1, c2 = SyncClient(Doc(client_id=101)), SyncClient(Doc(client_id=102))
        c3, c4 = SyncClient(Doc(client_id=103)), SyncClient(Doc(client_id=104))
        await c1.connect("127.0.0.1", port_a, "room")
        await c2.connect("127.0.0.1", port_a, "room")
        await c3.connect("127.0.0.1", port_b, "room")
        await c4.connect("127.0.0.1", port_b, "room")
        clients = (c1, c2, c3, c4)
        for c in clients:
            await c.pump(max_frames=4, timeout=0.3)

        # concurrent writes on both pods
        with c1.doc.transact() as txn:
            c1.doc.get_text("t").insert(txn, 0, "from-a1 ")
        with c3.doc.transact() as txn:
            c3.doc.get_text("t").insert(txn, 0, "from-b1 ")
        await c1.flush()
        await c3.flush()
        await asyncio.sleep(0.1)
        await _settle(rep, clients)

        with c2.doc.transact() as txn:
            t = c2.doc.get_text("t")
            t.insert(txn, len(t.get_string()), "a2-tail")
        await c2.flush()
        await asyncio.sleep(0.1)
        await _settle(rep, clients)

        states = [_full_state(c.doc) for c in clients]
        texts = [c.doc.get_text("t").get_string() for c in clients]
        assert len(set(texts)) == 1, texts
        assert "a2-tail" in texts[0] and "from-b1" in texts[0]
        # byte-identical full-state encodings at all four ends + both pods
        assert len(set(states)) == 1
        assert _full_state(pod_a.doc("room")) == states[0]
        assert _full_state(pod_b.doc("room")) == states[0]

        for c in clients:
            await c.close()
        await rep.close()
        for srv in (srv_a, srv_b):
            srv.close()
            await srv.wait_closed()

    run(main())


def test_diverged_pods_converge_via_greeting_sv_diff():
    async def main():
        pod_a, pod_b = SyncServer(), SyncServer()
        # diverge BEFORE any link exists
        with pod_a.doc("room").transact() as txn:
            pod_a.doc("room").get_text("t").insert(txn, 0, "alpha ")
        with pod_b.doc("room").transact() as txn:
            pod_b.doc("room").get_text("t").insert(txn, 0, "beta ")
        srv_b, port_b = await serve(pod_b)

        rep = Replicator(pod_a, "127.0.0.1", port_b)
        link = await rep.add_tenant("room")
        # greeting: both sides sent SyncStep1; pump answers + applies diffs
        for _ in range(4):
            await link.pump(timeout=0.15)
            await asyncio.sleep(0.05)

        sa = _full_state(pod_a.doc("room"))
        sb = _full_state(pod_b.doc("room"))
        assert sa == sb
        text = pod_a.doc("room").get_text("t").get_string()
        assert "alpha" in text and "beta" in text

        await rep.close()
        srv_b.close()
        await srv_b.wait_closed()

    run(main())


def test_gossip_repairs_dropped_broadcast():
    async def main():
        pod_a, pod_b = SyncServer(), SyncServer()
        srv_b, port_b = await serve(pod_b)
        rep = Replicator(pod_a, "127.0.0.1", port_b)
        link = await rep.add_tenant("room")
        for _ in range(3):
            await link.pump(timeout=0.1)

        # a local write lands in the link session's outbox; drop it on the
        # floor (simulated packet loss) instead of flushing
        with pod_a.doc("room").transact() as txn:
            pod_a.doc("room").get_text("t").insert(txn, 0, "lost?")
        dropped = pod_a.drain(link.session)
        assert dropped, "write should have queued a broadcast frame"
        await link.pump(timeout=0.1)
        assert pod_b.doc("room").get_text("t").get_string() == ""

        # anti-entropy: B cannot know it is missing data until it hears a
        # state vector. In the pod mesh each side runs its own replicator;
        # here B's repair round is its step1 --> A answers with the SV-diff.
        # Drive it through B's own link back to A.
        srv_a, port_a = await serve(pod_a)
        rep_b = Replicator(pod_b, "127.0.0.1", port_a)
        link_b = await rep_b.add_tenant("room")
        for _ in range(4):
            await link_b.pump(timeout=0.1)
            await link.pump(timeout=0.1)
            await asyncio.sleep(0.03)
        assert pod_b.doc("room").get_text("t").get_string() == "lost?"

        # and a later gossip round keeps already-converged pods quiet
        await link_b.gossip()
        await link_b.pump(timeout=0.15)
        assert _full_state(pod_a.doc("room")) == _full_state(pod_b.doc("room"))

        await rep.close()
        await rep_b.close()
        for srv in (srv_a, srv_b):
            srv.close()
            await srv.wait_closed()

    run(main())


def test_device_authoritative_pod_replicates_with_host_pod():
    from ytpu.sync.device_server import DeviceSyncServer

    async def main():
        pod_dev = DeviceSyncServer(
            n_docs=2, capacity=512, device_authoritative=True
        )
        pod_host = SyncServer()
        srv_h, port_h = await serve(pod_host)

        # the device pod replicates toward the host pod
        rep = Replicator(pod_dev, "127.0.0.1", port_h)
        link = await rep.add_tenant("room")

        # a client of the device pod writes
        c_dev = SyncClient(Doc(client_id=201))
        session, greeting = pod_dev.connect_frames("room")
        # in-process client of the device pod: drive frames directly
        with c_dev.doc.transact() as txn:
            c_dev.doc.get_text("t").insert(txn, 0, "device-born")
        from ytpu.core.state_vector import StateVector
        from ytpu.sync.protocol import Message, SyncMessage

        upd = c_dev.doc.encode_state_as_update_v1(StateVector({}))
        pod_dev.receive_frames(
            session, Message.sync(SyncMessage.update(upd)).encode_v1()
        )
        pod_dev.flush_device()

        # replicate to the host pod, then on to a host-pod client
        for _ in range(4):
            await link.pump(timeout=0.15)
            await asyncio.sleep(0.05)
        assert (
            pod_host.doc("room").get_text("t").get_string() == "device-born"
        )

        # reverse direction: host-pod write reaches the device batch
        with pod_host.doc("room").transact() as txn:
            t = pod_host.doc("room").get_text("t")
            t.insert(txn, len(t.get_string()), " host-born")
        # host pod's broadcast lands in its serve()-side session for the
        # link; a pump collects it
        for _ in range(4):
            await link.pump(timeout=0.15)
            await asyncio.sleep(0.05)
        pod_dev.flush_device()
        assert pod_dev.device_text("room") == "device-born host-born"
        assert int(np.asarray(pod_dev.ingestor.state.error).max()) == 0

        await rep.close()
        srv_h.close()
        await srv_h.wait_closed()

    run(main())
