"""Two-tier conflict scan (ISSUE-12 tentpole): adversarial deep-conflict
streams — N concurrent clients inserting at ONE origin, with interleaved
deletes and live moves — must integrate at byte parity with the serial
host oracle on the packed-XLA lane (and fused-interpret, where this jax
can run it), with the vectorized WIDE tier demonstrably firing (tier
counters > 0) and the dispatch-trip accounting coherent: the two-tier
dispatch never pays more serial `while_loop` trips than the
one-candidate-per-trip loop it replaces, and the scan-WIDTH record keeps
its pre-ISSUE-12 meaning (width still counts visited candidates, so the
histogram is tier-plan-invariant).

Every replay reuses the suite-wide (n_docs=2, capacity=256, chunk=16)
shape family — the compiled decode/chunk-step/compaction programs are
shared with test_async_overlap/test_chaos_recovery (distinct big
programs are the suite's scarce resource, conftest.py LLVM-arena note).
The tier-knob test necessarily compiles ONE extra plan variant (that is
the knob's documented retrace contract). The fused interpret test routes
through `tests/_fused_interpret.run_or_skip` and runs LAST.
"""

from functools import lru_cache

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    SCAN_TIER_CHEAP_DEFAULT,
    BatchEncoder,
    get_string,
    get_values,
    init_state,
    scan_tier_plan,
)
from ytpu.native import available as native_available
from ytpu.ops import integrate_kernel as ik
from ytpu.ops.integrate_kernel import replay_stream_fused
from ytpu.utils.faults import faults

from _fused_interpret import run_or_skip

# the ONE adversarial-stream generator, shared with the bench so the
# acceptance stream (benches/scan_tiers.py dry-run leg) and this file's
# parity streams can never drift apart (conftest puts the repo root on
# sys.path; benches/ is a namespace package)
from benches.scan_tiers import build_conflict_stream

needs_native = pytest.mark.skipif(
    not native_available(), reason="native codec unavailable (plan pre-scan)"
)

# the one shape family of this file (shared suite-wide)
N_DOCS, CAPACITY, CHUNK, D_BLOCK = 2, 256, 16, 2


@pytest.fixture(autouse=True)
def _clean_slate():
    """Armed faults and sticky lane demotions are process-global."""
    faults.clear()
    ik.reset_lane_health()
    yield
    faults.clear()
    ik.reset_lane_health()


def _capture(doc):
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    return log


def _stack(payloads, root_name="text"):
    enc = BatchEncoder(root_name=root_name)
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in payloads]
    return BatchEncoder.stack_steps(steps), enc


def _replay(stream, rank, lane="xla", interpret=False,
            max_capacity=4 * CAPACITY, policy=None):
    return replay_stream_fused(
        init_state(N_DOCS, CAPACITY),
        stream,
        rank,
        chunk_steps=CHUNK,
        d_block=D_BLOCK,
        lane=lane,
        interpret=interpret,
        max_capacity=max_capacity,
        policy=policy,
    )


@lru_cache(maxsize=1)
def _deep():
    """The file's main adversarial stream: 10 clients × 12 same-origin
    inserts (~120 concurrent siblings — widths ramp well past the
    default cheap bound of 32) + interleaved deletes."""
    payloads, expect = build_conflict_stream(
        10, 12, erase_every=5, erase_len=11
    )
    stream, enc = _stack(payloads)
    return payloads, expect, stream, enc


def test_deep_conflicts_wide_tier_fires_at_oracle_parity():
    """Tentpole acceptance: on an adversarial same-origin storm the
    packed-XLA lane stays byte-exact vs the serial host oracle AND the
    wide tier demonstrably fires — tier counters > 0, every scan lands
    in exactly one tier, and the two-tier dispatch pays strictly fewer
    serial while trips than the single-tier loop would have."""
    _, expect, stream, enc = _deep()
    st, stats = _replay(stream, enc.interner.rank_table())
    assert int(np.asarray(st.error).max()) == 0
    for d in range(N_DOCS):
        assert get_string(st, d, enc.payloads) == expect
    cheap_bound, _ = scan_tier_plan()
    assert cheap_bound == SCAN_TIER_CHEAP_DEFAULT  # suite runs defaults
    assert stats.scan_tier_wide > 0, stats
    assert stats.scan_tier_cheap > 0, stats  # the shallow mass stays cheap
    assert stats.scan_max > cheap_bound, stats
    assert stats.scan_tier_cheap + stats.scan_tier_wide == sum(
        stats.scan_hist
    ), stats
    assert (
        0 < stats.scan_trips_two_tier < stats.scan_trips_serial
    ), stats


def test_width_record_is_tier_plan_invariant(monkeypatch):
    """`scan_width_*` must keep its meaning (acceptance): replaying the
    SAME stream with the tier knob degenerated to the pre-ISSUE-12 loop
    (cheap=0, unroll=1 — every candidate is one while trip) yields an
    IDENTICAL width histogram/max, identical serial-trip accounting, and
    the degenerate plan pays exactly the serial trip count. Also pins
    the knob's documented env path: the driver re-reads it per chunk, so
    a changed value takes effect (via retrace) without a process
    restart."""
    _, expect, stream, enc = _deep()
    st_a, a = _replay(stream, enc.interner.rank_table())
    monkeypatch.setenv("YTPU_SCAN_TIER_CHEAP", "0")
    monkeypatch.setenv("YTPU_SCAN_WIDE_UNROLL", "1")
    assert scan_tier_plan() == (0, 1)
    st_b, b = _replay(stream, enc.interner.rank_table())
    assert get_string(st_b, 0, enc.payloads) == expect
    assert b.scan_hist == a.scan_hist, (a, b)
    assert b.scan_max == a.scan_max
    assert (b.scan_p50, b.scan_p99) == (a.scan_p50, a.scan_p99)
    assert b.scan_trips_serial == a.scan_trips_serial
    # degenerate plan = the old dispatch: one candidate per while trip
    assert b.scan_trips_two_tier == b.scan_trips_serial, b
    # the real plan strictly compresses the same workload
    assert a.scan_trips_two_tier < a.scan_trips_serial


def test_compaction_midstream_keeps_parity_and_tier_counts():
    """A tight-capacity storm (raw rows > capacity, growth disabled)
    must be carried by BETWEEN-CHUNK compaction while the wide tier is
    firing — the tier/trip meta words ride the packed meta through
    `compact_packed` untouched, so the record survives compaction."""
    payloads, expect = build_conflict_stream(
        8, 6, erase_every=1, rounds=6, typed=True, erase_len=5
    )
    stream, enc = _stack(payloads)
    raw_rows = int(np.asarray(stream.valid).sum())
    assert raw_rows > CAPACITY, "workload must not fit without compaction"
    st, stats = _replay(
        stream, enc.interner.rank_table(), max_capacity=CAPACITY
    )
    assert stats.compactions >= 1, stats
    assert stats.growths == 0, stats
    assert int(np.asarray(st.error).max()) == 0
    for d in range(N_DOCS):
        assert get_string(st, d, enc.payloads) == expect
    assert stats.scan_tier_wide > 0, stats
    assert stats.scan_tier_cheap + stats.scan_tier_wide == sum(
        stats.scan_hist
    ), stats


def test_live_moves_with_deep_conflicts_parity():
    """Concurrent same-origin ARRAY inserts + live `move_range_to`
    ranges + deletes: the scan walks move rows and tombstones in the
    conflict neighborhood, and move-claim recomputes run between chunks
    — parity vs the host oracle with the wide tier firing."""
    base = Doc(client_id=1)
    base_log = _capture(base)
    arr = base.get_array("a")
    with base.transact() as txn:
        for v in range(12):
            arr.push_back(txn, v)
    base_update = base.encode_state_as_update_v1()

    per_client = []
    for k in range(8):
        doc = Doc(client_id=10 + k)
        doc.apply_update_v1(base_update)
        log = _capture(doc)
        a = doc.get_array("a")
        for i in range(6):  # concurrent same-origin inserts at index 3
            with doc.transact() as txn:
                a.insert(txn, 3, 1000 * k + i)
        with doc.transact() as txn:  # a live move spanning the storm
            a.move_range_to(txn, 1, 3, len(a) - 1)
        if k % 3 == 0:
            with doc.transact() as txn:
                a.remove_range(txn, 2, 3)
        per_client.append(log)

    payloads = list(base_log)
    for i in range(max(len(log) for log in per_client)):
        for log in per_client:
            if i < len(log):
                payloads.append(log[i])
    oracle = Doc(client_id=2)
    for p in payloads:
        oracle.apply_update_v1(p)
    expect = oracle.get_array("a").to_json()

    stream, enc = _stack(payloads, root_name="a")
    st, stats = _replay(stream, enc.interner.rank_table())
    assert int(np.asarray(st.error).max()) == 0
    assert get_values(st, 0, enc.payloads) == expect
    assert get_values(st, 1, enc.payloads) == expect
    assert stats.scan_tier_wide > 0, stats
    assert stats.scan_trips_two_tier < stats.scan_trips_serial, stats


@needs_native
def test_demotion_ladder_carries_deep_conflicts_to_host_oracle():
    """PR-6 ladder under the reworked scan: an injected packed-XLA
    dispatch failure on the deep-conflict stream demotes past the
    driver's rungs to the serial host oracle, which completes the storm
    at byte parity (the ladder is scan-implementation-agnostic)."""
    from ytpu.models.replay import FusedReplay, plan_replay

    payloads, expect, _, _ = _deep()
    faults.arm("dispatch.fail", lane="xla")
    r = FusedReplay(
        n_docs=N_DOCS,
        plan=plan_replay(payloads),
        capacity=CAPACITY,
        max_capacity=4 * CAPACITY,
        d_block=D_BLOCK,
        chunk=CHUNK,
        lane="xla",
    )
    r.run(payloads)
    assert r.stats.final_lane == "host"
    assert r.get_string(0) == expect
    assert r.get_string(1) == expect


def test_fused_interpret_matches_xla_on_deep_conflicts():
    """Both lanes share the tier-plan statics and the meta record: where
    this jax build can interpret the Pallas kernel, the fused lane must
    byte-match the packed-XLA lane on the storm AND produce the same
    tier/trip words (the record is lane-agnostic by construction)."""
    _, expect, stream, enc = _deep()
    rank = enc.interner.rank_table()
    _, a = _replay(stream, rank)

    def go():
        return _replay(stream, rank, lane="fused", interpret=True)

    st_f, b = run_or_skip(go)
    assert get_string(st_f, 0, enc.payloads) == expect
    assert b.scan_hist == a.scan_hist
    assert b.scan_max == a.scan_max
    assert (b.scan_tier_cheap, b.scan_tier_wide) == (
        a.scan_tier_cheap, a.scan_tier_wide
    )
    assert (b.scan_trips_two_tier, b.scan_trips_serial) == (
        a.scan_trips_two_tier, a.scan_trips_serial
    )
