"""Doc-axis sub-batched integrate dispatch (ISSUE-20 tentpole): the
`SubBatchPlan`-driven slice loop inside `PackedReplayDriver` must be
BYTE-invisible — monolithic vs sub-batched replay produce identical
packed cols/meta and the identical ISSUE-13 commitment word — while
keeping every prior invariant alive: the PR-5 zero-sync lazy readout
(one drain, 12 d2h bytes per chunk readout, the per-slice words folded
on device), the PR-17 compile sentinel bound (ONE compiled family per
`(sub_width, capacity)` pair — slices never retrace), and the PR-6
ladder semantics (an armed `grow.oom` narrows the width in place
instead of killing the chunk: zero recoveries).

Every replay reuses the suite-wide (n_docs=2, capacity=256, chunk=16)
shape family for the MONOLITHIC side (the programs test_async_overlap /
test_scan_tiers already compiled) and forces width 1 via the budget
trick, so the file adds exactly one new big program — the (1, 256)
slice family; the slice boundary then sits between docs 0 and 1, inside
the broadcast storm (distinct big programs are the suite's scarce
resource, conftest.py LLVM-arena note). The narrowing test necessarily
uses its own small-capacity family: that IS the grow trajectory under
test. The fused-interpret parity test routes through
`tests/_fused_interpret.run_or_skip` and runs LAST.
"""

from functools import lru_cache

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import BatchEncoder, get_values, init_state
from ytpu.models.replay import FusedReplay, plan_replay, plan_subbatches
from ytpu.native import available as native_available
from ytpu.ops import integrate_kernel as ik
from ytpu.ops.integrate_kernel import packed_state_bytes
from ytpu.parallel import mesh as pmesh
from ytpu.utils import metrics
from ytpu.utils.capacity import HeadroomForecaster
from ytpu.utils.faults import faults
from ytpu.utils.phases import phases

from _fused_interpret import run_or_skip

# the ONE adversarial-stream generator shared with the bench (conftest
# puts the repo root on sys.path; benches/ is a namespace package)
from benches.scan_tiers import build_conflict_stream

needs_native = pytest.mark.skipif(
    not native_available(), reason="native codec unavailable (plan pre-scan)"
)

# the one shape family of this file (shared suite-wide)
N_DOCS, CAPACITY, CHUNK, D_BLOCK = 2, 256, 16, 2

# admits exactly width 1: slice state + its 2x grow transient
W1_BUDGET = packed_state_bytes(1, CAPACITY) + packed_state_bytes(
    1, 2 * CAPACITY
)


@pytest.fixture(autouse=True)
def _clean_slate():
    """Armed faults and sticky lane demotions are process-global."""
    faults.clear()
    ik.reset_lane_health()
    yield
    faults.clear()
    ik.reset_lane_health()


def _capture(doc):
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    return log


@lru_cache(maxsize=1)
def _typing():
    """Append-typing + tail erase (the test_async_overlap workload):
    tombstones are clock- AND sequence-contiguous, so `compact_packed`
    reclaims them and a max_capacity == capacity replay is carried by
    compaction alone; the 3-chunk prefix is the zero-sync steady
    state."""
    import bench as _bench

    ops = []
    length = 0
    for _ in range(14):
        for i in range(20):
            ops.append(("i", length, "abcdef"[i % 6]))
            length += 1
        ops.append(("d", length - 18, 18))
        length -= 18
    log, expect = _bench.build_updates(ops)
    return log, expect, plan_replay(log)


@lru_cache(maxsize=1)
def _storm():
    """Same-origin conflict storm (the test_scan_tiers `_deep` shape,
    sized down): ~64 concurrent siblings all anchored on one origin —
    every doc is hot, and under a width-1 plan the slice boundary cuts
    straight through the broadcast storm."""
    payloads, expect = build_conflict_stream(
        8, 8, erase_every=5, erase_len=11
    )
    return payloads, expect, plan_replay(payloads)


def _make(plan, shard: bool, max_capacity: int = 4 * CAPACITY, **kw):
    kw.setdefault("lane", "xla")
    if shard:
        kw.setdefault(
            "forecaster", HeadroomForecaster(budget_bytes=W1_BUDGET)
        )
    return FusedReplay(
        n_docs=N_DOCS,
        plan=plan,
        capacity=CAPACITY,
        max_capacity=max_capacity,
        d_block=D_BLOCK,
        chunk=CHUNK,
        overlap=True,
        ingest="raw",
        sync_per_chunk=False,
        shard_docs=shard,
        **kw,
    )


def _byte_parity(a: FusedReplay, b: FusedReplay) -> None:
    assert np.array_equal(np.asarray(a.cols), np.asarray(b.cols))
    assert np.array_equal(np.asarray(a.meta), np.asarray(b.meta))
    assert a.stats.commit_word == b.stats.commit_word


def test_plan_subbatches_pow2_divisibility_and_floor():
    """The plan is pure host arithmetic: width is always a pow2 that
    divides the doc axis (ONE shape family serves every slice), the
    budget trick admits exactly the intended width, and the floor is
    `d_block` even when infeasible."""
    budget = 3 * packed_state_bytes(768, 512)
    p = plan_subbatches(1024, 512, d_block=8, budget_bytes=budget)
    assert (p.width, p.n_sub) == (512, 2)
    assert p.feasible and not p.monolithic
    assert p.transient_bytes <= budget < p.monolithic_bytes
    wide = plan_subbatches(8192, 512, d_block=8, budget_bytes=budget)
    assert (wide.width, wide.n_sub) == (512, 16)
    # pow2 + divisibility hold on a non-pow2 doc axis too
    odd = plan_subbatches(6, 256, budget_bytes=1 << 40)
    assert (odd.width, odd.n_sub) == (2, 3)
    assert odd.n_docs % odd.width == 0
    # the budget trick used suite-wide: transient(w) admits exactly w
    forced = plan_subbatches(N_DOCS, CAPACITY, budget_bytes=W1_BUDGET)
    assert forced.width == 1 and forced.n_sub == 2
    assert forced.transient_bytes == W1_BUDGET
    # floor: the fused lane cannot tile below d_block — plan reports
    # the bust via `feasible` instead of returning an untileable width
    floored = plan_subbatches(1024, 512, d_block=8, budget_bytes=1)
    assert floored.width == 8 and not floored.feasible
    # a huge budget degenerates to the PR-5 monolithic dispatch
    mono = plan_subbatches(1024, 512, budget_bytes=1 << 50)
    assert mono.monolithic and mono.n_sub == 1
    # max_width caps the start even when the budget would allow more
    capped = plan_subbatches(1024, 512, budget_bytes=1 << 50, max_width=256)
    assert capped.width == 256 and capped.n_sub == 4


def test_single_device_mesh_fallback_is_identity():
    """CPU tier-1 runs on one device: every batch-dim sharding helper
    must degrade to a no-op so the sub-batch loop is placement-free and
    byte-identical to the unsharded path."""
    import jax

    if len(jax.devices()) != 1:
        pytest.skip("multi-device host: fallback path not reachable")
    assert pmesh.batch_mesh() is None
    assert pmesh.batch_mesh(n_devices=1) is None
    assert pmesh.subbatch_devices(4) is None
    probe = np.arange(8)
    assert pmesh.shard_docs_put(probe) is probe


@needs_native
def test_subbatch_parity_with_compaction_midstream():
    """Tentpole acceptance: a tight-capacity typing stream (growth
    disabled — BETWEEN-CHUNK compaction carries it, running per doc
    slice under the width-1 plan) must be BYTE-identical to the
    monolithic replay."""
    log, expect, plan = _typing()
    mono = _make(plan, shard=False, max_capacity=CAPACITY)
    mono.run(log)
    sub = _make(plan, shard=True, max_capacity=CAPACITY)
    sub.run(log)
    assert sub.stats.subbatch_width == 1, sub.stats
    assert mono.stats.compactions >= 1 and sub.stats.compactions >= 1
    assert sub.stats.growths == 0, sub.stats
    _byte_parity(mono, sub)
    for d in range(N_DOCS):
        assert sub.get_string(d) == mono.get_string(d) == expect


@needs_native
def test_subbatch_boundary_splits_conflict_storm():
    """A same-origin conflict storm broadcast to every doc, replayed
    with the slice boundary cutting the batch in half: each per-slice
    dispatch integrates the same ~64-sibling scan, and the result is
    byte-identical to the monolithic replay — the storm never sees the
    seam (docs 0 and 1 sit in different slices)."""
    payloads, expect, plan = _storm()
    mono = _make(plan, shard=False)
    mono.run(payloads)
    sub = _make(plan, shard=True)
    sub.run(payloads)
    assert sub.stats.subbatch_width == 1, sub.stats
    _byte_parity(mono, sub)
    for d in range(N_DOCS):
        assert sub.get_string(d) == mono.get_string(d) == expect
    assert sub.get_string(0) == sub.get_string(1)


def test_subbatch_parity_with_live_moves():
    """Array storm with live `move_range_to` ranges through the STREAM
    path (`replay_stream_fused(shard_docs=True)` — mixed content can't
    ride the text-only byte path): the between-chunk grow/compact run
    per doc slice under a budget that forces width 1, and the packed
    planes stay byte-identical to the monolithic replay."""
    from ytpu.ops.integrate_kernel import pack_state, replay_stream_fused

    base = Doc(client_id=1)
    base_log = _capture(base)
    arr = base.get_array("a")
    with base.transact() as txn:
        for v in range(12):
            arr.push_back(txn, v)
    base_update = base.encode_state_as_update_v1()

    per_client = []
    for k in range(8):
        doc = Doc(client_id=10 + k)
        doc.apply_update_v1(base_update)
        log = _capture(doc)
        a = doc.get_array("a")
        for i in range(8):
            with doc.transact() as txn:
                a.insert(txn, 3, 1000 * k + i)
        with doc.transact() as txn:
            a.move_range_to(txn, 1, 3, len(a) - 1)
        if k % 3 == 0:
            with doc.transact() as txn:
                a.remove_range(txn, 2, 3)
        per_client.append(log)

    payloads = list(base_log)
    for i in range(max(len(log) for log in per_client)):
        for log in per_client:
            if i < len(log):
                payloads.append(log[i])
    oracle = Doc(client_id=2)
    for p in payloads:
        oracle.apply_update_v1(p)
    expect = oracle.get_array("a").to_json()
    enc = BatchEncoder(root_name="a")
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in payloads]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    tight = 64  # raw rows exceed it: the grow path MUST fire per slice
    assert int(np.asarray(stream.valid).sum()) > tight

    def replay(shard: bool):
        kw = {}
        if shard:
            b = packed_state_bytes(1, tight) + packed_state_bytes(
                1, 2 * tight
            )
            kw = dict(
                shard_docs=True,
                forecaster=HeadroomForecaster(budget_bytes=b),
            )
        return replay_stream_fused(
            init_state(N_DOCS, tight),
            stream,
            rank,
            chunk_steps=CHUNK,
            d_block=D_BLOCK,
            lane="xla",
            max_capacity=4 * CAPACITY,
            **kw,
        )

    st_a, a = replay(shard=False)
    st_b, b = replay(shard=True)
    assert a.growths >= 1 and b.growths >= 1, (a, b)
    assert b.subbatch_width == 1, b
    for pa, pb in zip(pack_state(st_a), pack_state(st_b)):
        assert np.array_equal(np.asarray(pa), np.asarray(pb))
    assert get_values(st_b, 0, enc.payloads) == expect
    assert get_values(st_b, N_DOCS - 1, enc.payloads) == expect


@needs_native
def test_subbatch_zero_sync_and_compile_family_bound():
    """The two load-bearing invariants of the slice loop: (1) the PR-5
    zero-sync readout survives the fold — per-slice readout words merge
    ON DEVICE into one `[N_READOUT]` surface per chunk, so the steady
    state still drains ONCE with 12 d2h bytes per chunk readout; (2)
    the PR-17 sentinel sees exactly ONE `replay.subbatch` compile event
    for the whole run (one `(sub_width, capacity)` family, zero
    retraces) even though every chunk pays n_sub slice dispatches."""
    log, expect, plan = _typing()
    prefix = log[: 3 * CHUNK]
    mono = _make(plan, shard=False)
    mono.run(prefix)
    phases.reset()
    phases.enable()
    try:
        marker = phases.compile_marker()
        sub = _make(plan, shard=True)
        stats = sub.run(prefix)
        snap = phases.snapshot()
        events = [
            e
            for e in phases.compile_events(marker)
            if e["program"] == "replay.subbatch"
        ]
    finally:
        phases.disable()
        phases.reset()
    assert stats.chunks == 3 and stats.subbatch_width == 1, stats
    assert stats.syncs == 1, f"steady state must drain once, got {stats}"
    # one folded readout per chunk, all materialized in the one drain
    assert snap["replay.readout"]["d2h_bytes"] == 12 * stats.chunks, snap
    # 3 chunks x 2 slices = 6 dispatches, ONE compiled family, 0 retraces
    assert len(events) == 1, events
    assert not events[0]["retrace"], events
    assert snap["subbatch.width"]["value"] == 1.0, snap
    assert snap["subbatch.n_sub"]["value"] == 2.0, snap
    for d in range(N_DOCS):
        assert sub.get_string(d) == mono.get_string(d)


@needs_native
def test_grow_oom_narrows_instead_of_killing_chunk():
    """Satellite acceptance: an armed ``grow.oom`` under `shard_docs`
    demotes the width in place (journaled, counted
    `capacity.subbatch_narrowed`) and the grow RETRIES and succeeds —
    the chunk is never killed, so the PR-6 recovery ladder stays cold
    (zero recoveries), unlike the monolithic path where the same fault
    costs a ReplayFault recovery."""
    import bench as _bench

    grow_log, grow_expect = _bench.build_updates(
        [("i", 0, "abcdefgh") for _ in range(40)]
    )
    grow_plan = plan_replay(grow_log)

    def replay():
        r = FusedReplay(
            n_docs=N_DOCS,
            plan=grow_plan,
            capacity=32,
            max_capacity=1024,
            d_block=D_BLOCK,
            chunk=8,
            lane="xla",
            overlap=True,
            ingest="raw",
            sync_per_chunk=False,
            shard_docs=True,
            forecaster=HeadroomForecaster(budget_bytes=1 << 30),
        )
        r.run(grow_log)
        return r

    before = metrics.counter("capacity.subbatch_narrowed").value
    faults.arm("grow.oom")
    try:
        r = replay()
    finally:
        faults.clear()
    narrowed = metrics.counter("capacity.subbatch_narrowed").value - before
    assert narrowed >= 1, "armed grow.oom never narrowed the sub-batch"
    assert r.stats.subbatch_narrowed == narrowed, r.stats
    assert r.stats.growths >= 1, r.stats
    assert r.stats.recoveries == 0, (
        "narrowing must absorb the denial in place",
        r.stats,
    )
    assert r.get_string(0) == grow_expect == r.get_string(N_DOCS - 1)
    # an un-faulted run on the same family narrows nothing
    clean = replay()
    assert clean.stats.subbatch_narrowed == 0, clean.stats
    assert clean.get_string(0) == grow_expect


@needs_native
def test_subbatch_fused_interpret_or_skip():
    """The fused Pallas lane through the sliced loop — or a SKIP when
    this container's jax cannot interpret the kernel (memoized across
    files by tests/_fused_interpret). The fused floor is `d_block`, so
    this leg needs 4 docs for a real width-2 slice boundary (one
    `d_block` tile per slice); the extra family only compiles where
    fused-interpret actually runs. Runs LAST."""
    log, expect, plan = _typing()
    prefix = log[: 2 * CHUNK]
    budget = packed_state_bytes(2, CAPACITY) + packed_state_bytes(
        2, 2 * CAPACITY
    )

    def go():
        r = FusedReplay(
            n_docs=4,
            plan=plan,
            capacity=CAPACITY,
            max_capacity=4 * CAPACITY,
            d_block=D_BLOCK,
            chunk=CHUNK,
            lane="fused",
            interpret=True,
            overlap=True,
            ingest="raw",
            sync_per_chunk=False,
            shard_docs=True,
            forecaster=HeadroomForecaster(budget_bytes=budget),
        )
        r.run(prefix)
        return r

    sub = run_or_skip(go)
    assert sub.stats.subbatch_width == 2, sub.stats
    # the xla monolithic twin (compiled only where fused-interpret ran)
    mono = FusedReplay(
        n_docs=4,
        plan=plan,
        capacity=CAPACITY,
        max_capacity=4 * CAPACITY,
        d_block=D_BLOCK,
        chunk=CHUNK,
        lane="xla",
        overlap=True,
        ingest="raw",
        sync_per_chunk=False,
    )
    mono.run(prefix)
    for d in range(4):
        assert sub.get_string(d) == mono.get_string(d)
