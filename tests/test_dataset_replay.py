"""Replay of real Yjs-generated datasets and editing traces.

- small-test-dataset.bin: sequences of Yjs updates + expected text/map/array
  state after each run (format per reference compatibility_tests.rs:437-476).
- sequential editing traces: (pos, del, ins) patch streams replayed through
  Text (format per reference tests/edit_traces.rs:16-36, tests at
  edit_traces_tests.rs:1-60).

These read the reference's asset files directly (read-only test data);
they skip when the assets are not present.
"""

import gzip
import json
import os

import pytest

from ytpu.core import Doc, Update
from ytpu.encoding.lib0 import Cursor, read_any

ASSETS = "/root/reference/assets"

requires_assets = pytest.mark.skipif(
    not os.path.isdir(ASSETS), reason="reference assets not available"
)


@requires_assets
def test_small_data_set():
    with open(f"{ASSETS}/bench-input/small-test-dataset.bin", "rb") as f:
        cur = Cursor(f.read())
    test_count = cur.read_var_uint()
    for test_num in range(test_count):
        updates_len = cur.read_var_uint()
        doc = Doc(client_id=0xFFFF)
        txt = doc.get_text("text")
        m = doc.get_map("map")
        arr = doc.get_array("array")
        for _ in range(updates_len):
            payload = cur.read_buf()
            doc.apply_update_v1(payload)
        expected_text = cur.read_string()
        assert txt.get_string() == expected_text, f"text mismatch in run {test_num}"
        expected_map = read_any(cur)
        assert m.to_json() == expected_map, f"map mismatch in run {test_num}"
        expected_arr = read_any(cur)
        assert arr.to_json() == expected_arr, f"array mismatch in run {test_num}"


def _replay_trace(name: str, limit: int = None):
    path = f"{ASSETS}/editing-traces/sequential_traces/{name}.json.gz"
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    doc = Doc(client_id=1)
    txt = doc.get_text("text")
    txns = data["txns"]
    if limit is not None:
        txns = txns[:limit]
    for txn_data in txns:
        with doc.transact() as txn:
            for pos, del_len, ins in txn_data["patches"]:
                if del_len:
                    txt.remove_range(txn, pos, del_len)
                if ins:
                    txt.insert(txn, pos, ins)
    return doc, txt, data


@requires_assets
def test_trace_friendsforever_prefix():
    # full final-content check only when replaying the entire trace; here we
    # replay a prefix for test-suite speed and assert consistency invariants
    doc, txt, data = _replay_trace("friendsforever_flat", limit=2000)
    s = txt.get_string()
    assert len(txt) == len(s)  # ascii trace: utf16 == python len
    # re-encode + re-apply must reproduce the same state
    clone = Doc(client_id=2)
    clone.apply_update_v1(doc.encode_state_as_update_v1())
    assert clone.get_text("text").get_string() == s


@requires_assets
def test_trace_sveltecomponent_full():
    doc, txt, data = _replay_trace("sveltecomponent")
    assert txt.get_string() == data["endContent"]


@requires_assets
def test_concurrent_trace_friendsforever_prefix():
    """Replay the CONCURRENT friendsforever trace (2 agents, parents DAG):
    each transaction forks from the merge of its parents' states, edits,
    and re-encodes; all heads must merge to one convergent document
    (format: assets/editing-traces/concurrent_traces/README.md)."""
    path = f"{ASSETS}/editing-traces/concurrent_traces/friendsforever.json.gz"
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    txns = data["txns"][:400]

    # last index that needs each state, so memory stays bounded
    last_use = {}
    for i, t in enumerate(txns):
        for p in t["parents"]:
            if p < len(txns):
                last_use[p] = i

    states = {}
    for i, t in enumerate(txns):
        doc = Doc(client_id=int(t["agent"]) + 1)
        for p in t["parents"]:
            doc.apply_update_v1(states[p])
        txt = doc.get_text("text")
        with doc.transact() as txn:
            for pos, del_len, ins in t["patches"]:
                if del_len:
                    txt.remove_range(txn, pos, del_len)
                if ins:
                    txt.insert(txn, pos, ins)
        states[i] = doc.encode_state_as_update_v1()
        for p in t["parents"]:
            if last_use.get(p) == i:
                states.pop(p, None)

    heads = [i for i in range(len(txns)) if i in states]
    final = Doc(client_id=0xF00D)
    for h in heads:
        final.apply_update_v1(states[h])
    s = final.get_text("text").get_string()
    # a replica applying the same heads in reverse converges identically
    replica = Doc(client_id=0xBEEF)
    for h in reversed(heads):
        replica.apply_update_v1(states[h])
    assert replica.get_text("text").get_string() == s
    assert len(s) > 0
    assert final.store.pending is None


@requires_assets
def test_concurrent_trace_full_end_content():
    """Full concurrent replay: the merge of all heads equals endContent."""
    path = f"{ASSETS}/editing-traces/concurrent_traces/friendsforever.json.gz"
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    txns = data["txns"]

    last_use = {}
    for i, t in enumerate(txns):
        for p in t["parents"]:
            last_use[p] = i

    states = {}
    for i, t in enumerate(txns):
        doc = Doc(client_id=int(t["agent"]) + 1)
        for p in t["parents"]:
            doc.apply_update_v1(states[p])
        txt = doc.get_text("text")
        with doc.transact() as txn:
            for pos, del_len, ins in t["patches"]:
                if del_len:
                    txt.remove_range(txn, pos, del_len)
                if ins:
                    txt.insert(txn, pos, ins)
        states[i] = doc.encode_state_as_update_v1()
        for p in t["parents"]:
            if last_use.get(p) == i:
                states.pop(p, None)

    final = Doc(client_id=0xF00D)
    for i in sorted(states):
        final.apply_update_v1(states[i])
    assert final.get_text("text").get_string() == data["endContent"]
