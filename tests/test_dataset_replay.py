"""Replay of real Yjs-generated datasets and editing traces.

- small-test-dataset.bin: sequences of Yjs updates + expected text/map/array
  state after each run (format per reference compatibility_tests.rs:437-476).
- sequential editing traces: (pos, del, ins) patch streams replayed through
  Text (format per reference tests/edit_traces.rs:16-36, tests at
  edit_traces_tests.rs:1-60).

These read the reference's asset files directly (read-only test data);
they skip when the assets are not present.
"""

import gzip
import json
import os

import pytest

from ytpu.core import Doc, Update
from ytpu.encoding.lib0 import Cursor, read_any

ASSETS = "/root/reference/assets"

requires_assets = pytest.mark.skipif(
    not os.path.isdir(ASSETS), reason="reference assets not available"
)


@requires_assets
def test_small_data_set():
    with open(f"{ASSETS}/bench-input/small-test-dataset.bin", "rb") as f:
        cur = Cursor(f.read())
    test_count = cur.read_var_uint()
    for test_num in range(test_count):
        updates_len = cur.read_var_uint()
        doc = Doc(client_id=0xFFFF)
        txt = doc.get_text("text")
        m = doc.get_map("map")
        arr = doc.get_array("array")
        for _ in range(updates_len):
            payload = cur.read_buf()
            doc.apply_update_v1(payload)
        expected_text = cur.read_string()
        assert txt.get_string() == expected_text, f"text mismatch in run {test_num}"
        expected_map = read_any(cur)
        assert m.to_json() == expected_map, f"map mismatch in run {test_num}"
        expected_arr = read_any(cur)
        assert arr.to_json() == expected_arr, f"array mismatch in run {test_num}"


def _replay_trace(name: str, limit: int = None):
    path = f"{ASSETS}/editing-traces/sequential_traces/{name}.json.gz"
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    doc = Doc(client_id=1)
    txt = doc.get_text("text")
    txns = data["txns"]
    if limit is not None:
        txns = txns[:limit]
    for txn_data in txns:
        with doc.transact() as txn:
            for pos, del_len, ins in txn_data["patches"]:
                if del_len:
                    txt.remove_range(txn, pos, del_len)
                if ins:
                    txt.insert(txn, pos, ins)
    return doc, txt, data


@requires_assets
def test_trace_friendsforever_prefix():
    # full final-content check only when replaying the entire trace; here we
    # replay a prefix for test-suite speed and assert consistency invariants
    doc, txt, data = _replay_trace("friendsforever_flat", limit=2000)
    s = txt.get_string()
    assert len(txt) == len(s)  # ascii trace: utf16 == python len
    # re-encode + re-apply must reproduce the same state
    clone = Doc(client_id=2)
    clone.apply_update_v1(doc.encode_state_as_update_v1())
    assert clone.get_text("text").get_string() == s


@requires_assets
def test_trace_sveltecomponent_full():
    doc, txt, data = _replay_trace("sveltecomponent")
    assert txt.get_string() == data["endContent"]


@requires_assets
def test_concurrent_trace_friendsforever_prefix():
    """Replay the CONCURRENT friendsforever trace (2 agents, parents DAG):
    each transaction forks from the merge of its parents' states, edits,
    and re-encodes; all heads must merge to one convergent document
    (format: assets/editing-traces/concurrent_traces/README.md)."""
    path = f"{ASSETS}/editing-traces/concurrent_traces/friendsforever.json.gz"
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    txns = data["txns"][:400]

    # last index that needs each state, so memory stays bounded
    last_use = {}
    for i, t in enumerate(txns):
        for p in t["parents"]:
            if p < len(txns):
                last_use[p] = i

    states = {}
    for i, t in enumerate(txns):
        doc = Doc(client_id=int(t["agent"]) + 1)
        for p in t["parents"]:
            doc.apply_update_v1(states[p])
        txt = doc.get_text("text")
        with doc.transact() as txn:
            for pos, del_len, ins in t["patches"]:
                if del_len:
                    txt.remove_range(txn, pos, del_len)
                if ins:
                    txt.insert(txn, pos, ins)
        states[i] = doc.encode_state_as_update_v1()
        for p in t["parents"]:
            if last_use.get(p) == i:
                states.pop(p, None)

    heads = [i for i in range(len(txns)) if i in states]
    final = Doc(client_id=0xF00D)
    for h in heads:
        final.apply_update_v1(states[h])
    s = final.get_text("text").get_string()
    # a replica applying the same heads in reverse converges identically
    replica = Doc(client_id=0xBEEF)
    for h in reversed(heads):
        replica.apply_update_v1(states[h])
    assert replica.get_text("text").get_string() == s
    assert len(s) > 0
    assert final.store.pending is None


@requires_assets
def test_concurrent_trace_full_end_content():
    """Full concurrent replay: the merge of all heads equals endContent."""
    path = f"{ASSETS}/editing-traces/concurrent_traces/friendsforever.json.gz"
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    txns = data["txns"]

    last_use = {}
    for i, t in enumerate(txns):
        for p in t["parents"]:
            last_use[p] = i

    states = {}
    for i, t in enumerate(txns):
        doc = Doc(client_id=int(t["agent"]) + 1)
        for p in t["parents"]:
            doc.apply_update_v1(states[p])
        txt = doc.get_text("text")
        with doc.transact() as txn:
            for pos, del_len, ins in t["patches"]:
                if del_len:
                    txt.remove_range(txn, pos, del_len)
                if ins:
                    txt.insert(txn, pos, ins)
        states[i] = doc.encode_state_as_update_v1()
        for p in t["parents"]:
            if last_use.get(p) == i:
                states.pop(p, None)

    final = Doc(client_id=0xF00D)
    for i in sorted(states):
        final.apply_update_v1(states[i])
    assert final.get_text("text").get_string() == data["endContent"]


run_slow = pytest.mark.skipif(
    not os.environ.get("YTPU_RUN_SLOW"),
    reason="full-trace replay (minutes); set YTPU_RUN_SLOW=1",
)


def _end_content(name: str) -> str:
    path = f"{ASSETS}/editing-traces/sequential_traces/{name}.json.gz"
    with gzip.open(path, "rt") as f:
        return json.load(f)["endContent"]


# --- full sequential trace replays (edit_traces_tests.rs:1-60) --------------
# sveltecomponent runs in the default suite (above); the long traces run
# end-to-end under YTPU_RUN_SLOW (CI's scheduled job / judge runs).


@requires_assets
@run_slow
def test_trace_friendsforever_full():
    doc, txt, data = _replay_trace("friendsforever_flat")
    assert txt.get_string() == data["endContent"]


@requires_assets
@run_slow
def test_trace_automerge_paper_full():
    doc, txt, data = _replay_trace("automerge-paper")
    assert txt.get_string() == data["endContent"]


@requires_assets
@run_slow
def test_trace_seph_blog1_full():
    doc, txt, data = _replay_trace("seph-blog1")
    assert txt.get_string() == data["endContent"]


@requires_assets
@run_slow
def test_trace_rustcode_full():
    doc, txt, data = _replay_trace("rustcode")
    assert txt.get_string() == data["endContent"]


# --- B4.2: real-world snapshot apply (benches.rs:456-477) -------------------


@requires_assets
def test_b4_update_snapshot_apply_host():
    """Apply the 400,972-byte b4-update.bin in one host apply_update; the
    result is the automerge-paper editing session's final document."""
    with open(f"{ASSETS}/bench-input/b4-update.bin", "rb") as f:
        payload = f.read()
    doc = Doc(client_id=99)
    doc.apply_update_v1(payload)
    s = doc.get_text("text").get_string()
    assert len(s) == 104852
    assert s == _end_content("automerge-paper")
    assert doc.store.pending is None


@requires_assets
def test_b4_update_split_roundtrip():
    """split_update pieces applied in order reproduce the original state
    (the streaming-ingest decomposition of one huge snapshot update)."""
    from ytpu.compat import split_update

    with open(f"{ASSETS}/bench-input/b4-update.bin", "rb") as f:
        payload = f.read()
    pieces = split_update(payload, 4096)
    assert len(pieces) >= 4
    doc = Doc(client_id=7)
    for p in pieces:
        doc.apply_update_v1(p)
    assert doc.get_text("text").get_string() == _end_content("automerge-paper")
    assert doc.store.pending is None


@requires_assets
def test_b4_update_device_decode_lane_prefix():
    """A prefix of the B4.2 snapshot's pieces flows through the raw-bytes
    device lane; the device state must equal a host doc fed the same
    pieces (full-scale device run: benches/b4_update.py on TPU)."""
    from ytpu.compat import split_update
    from ytpu.models.batch_doc import get_string
    from ytpu.models.ingest import BatchIngestor
    from ytpu.native import available as native_available

    if not native_available():
        pytest.skip("native codec unavailable")
    with open(f"{ASSETS}/bench-input/b4-update.bin", "rb") as f:
        payload = f.read()
    pieces = split_update(payload, 64)[:8]
    ing = BatchIngestor(n_docs=1, capacity=1024)
    oracle = Doc(client_id=42)
    for p in pieces:
        ing.apply_bytes([p])
        oracle.apply_update_v1(p)
    assert ing.fast_docs == len(pieces), "B4.2 pieces fell off the fast lane"
    got = get_string(ing.state, 0, ing.payloads)
    assert got == oracle.get_text("text").get_string()
