"""Native (C++) batched encode finisher — byte parity vs the Python finisher.

`finish_encode_diff_batch` must emit byte-identical v1 payloads to
`finish_encode_diff` for every supported row shape (VERDICT r2 #6;
reference equivalent: store.rs:204-248). Docs outside the native scope
fall back per doc, so the batch API is *always* byte-equal; these tests
additionally pin that the native path (not the fallback) handled the
common shapes, via the library's status codes.
"""

import jax
import numpy as np
import pytest

from ytpu.core import Doc, StateVector, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    encode_diff_batch,
    finish_encode_diff,
    finish_encode_diff_batch,
    init_state,
)
from ytpu.native import available as native_available

needs_native = pytest.mark.skipif(
    not native_available(), reason="native library unavailable"
)


def build_device_docs(edit_fns, capacity=128, root="text"):
    """Host docs per slot + a device-state mirror (enc, state)."""
    docs, logs = [], []
    for i, fn in enumerate(edit_fns):
        d = Doc(client_id=i + 1)
        log = []
        d.observe_update_v1(lambda p, o, t, log=log: log.append(p))
        fn(d)
        docs.append(d)
        logs.append(log)
    enc = BatchEncoder(root_name=root)
    state = init_state(len(docs), capacity)
    max_steps = max(len(lg) for lg in logs)
    for step in range(max_steps):
        updates = [
            Update.decode_v1(lg[step]) if step < len(lg) else None for lg in logs
        ]
        batch = enc.build_batch(updates, n_rows=8, n_dels=4)
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    assert int(np.asarray(state.error).max()) == 0
    return docs, state, enc


def diff_arrays(state, enc, remote):
    n_clients = remote.shape[1]
    ship, offsets, _sv, deleted = jax.tree_util.tree_map(
        np.asarray, encode_diff_batch(state, remote, n_clients)
    )
    return ship, offsets, deleted


def assert_parity(state, docs_idx, ship, offsets, deleted, enc, payloads=None):
    native = finish_encode_diff_batch(
        state, docs_idx, ship, offsets, deleted, enc, payloads
    )
    for i, d in enumerate(docs_idx):
        oracle = finish_encode_diff(
            state, d, ship, offsets, deleted, enc, payloads
        )
        assert native[i] == oracle, (
            f"doc {d}: native {native[i].hex()} != python {oracle.hex()}"
        )
    return native


def native_statuses(state, docs_idx, ship, offsets, deleted, enc, payloads=None):
    """Which docs the C++ core handled itself (0) vs punted (1).  Reads
    the module's `LAST_FINISH_STATUSES` introspection surface — the
    vectorized span readout (ISSUE-10) no longer makes per-doc
    `ytpu_finish_status` calls a spy could intercept."""
    from ytpu.models import batch_doc as bd

    bd.finish_encode_diff_batch(
        state, docs_idx, ship, offsets, deleted, enc, payloads
    )
    return list(bd.LAST_FINISH_STATUSES)


@needs_native
def test_text_parity_full_state():
    def edits(chunks):
        def fn(d):
            t = d.get_text("text")
            for pos, chunk in chunks:
                with d.transact() as txn:
                    t.insert(txn, pos, chunk)

        return fn

    docs, state, enc = build_device_docs(
        [
            edits([(0, "hello"), (5, " world")]),
            edits([(0, "doc-two"), (3, "✓🙂")]),
            edits([(0, "abc"), (0, "xyz"), (3, "mid")]),
        ]
    )
    remote = np.zeros((len(docs), 8), dtype=np.int32)
    ship, offsets, deleted = diff_arrays(state, enc, remote)
    payloads_list = assert_parity(
        state, list(range(len(docs))), ship, offsets, deleted, enc
    )
    # each payload replays into a correct replica
    for i, doc in enumerate(docs):
        replica = Doc(client_id=99)
        replica.apply_update_v1(payloads_list[i])
        assert (
            replica.get_text("text").get_string()
            == doc.get_text("text").get_string()
        )
    # the native core (not the Python fallback) must have produced these
    assert native_statuses(
        state, list(range(len(docs))), ship, offsets, deleted, enc
    ) == [0, 0, 0]


@needs_native
def test_text_parity_offset_trimmed():
    """A remote with partial coverage forces first-block offset trimming,
    including a boundary inside a surrogate pair."""

    def fn(d):
        t = d.get_text("text")
        with d.transact() as txn:
            t.insert(txn, 0, "ab🙂cd")  # 🙂 = 2 UTF-16 units at clocks 2-3

    docs, state, enc = build_device_docs([fn])
    cidx = enc.interner.to_idx[1]
    for cut in (1, 2, 3, 4):  # clock 3 lands inside the surrogate pair
        remote = np.zeros((1, 8), dtype=np.int32)
        remote[0, cidx] = cut
        ship, offsets, deleted = diff_arrays(state, enc, remote)
        assert_parity(state, [0], ship, offsets, deleted, enc)


@needs_native
def test_delete_set_parity():
    def fn(d):
        t = d.get_text("text")
        with d.transact() as txn:
            t.insert(txn, 0, "0123456789")
        with d.transact() as txn:
            t.remove_range(txn, 2, 3)
        with d.transact() as txn:
            t.remove_range(txn, 4, 2)

    docs, state, enc = build_device_docs([fn])
    remote = np.zeros((1, 8), dtype=np.int32)
    ship, offsets, deleted = diff_arrays(state, enc, remote)
    out = assert_parity(state, [0], ship, offsets, deleted, enc)
    replica = Doc(client_id=99)
    replica.apply_update_v1(out[0])
    assert (
        replica.get_text("text").get_string()
        == docs[0].get_text("text").get_string()
    )


@needs_native
def test_map_and_any_parity():
    """Map rows (parent_sub keys), ContentAny scalars/arrays, binary and
    embed payloads — host refs resolved through the pre-baked arenas."""
    from ytpu.types.shared import MapPrelim

    def fn(d):
        m = d.get_map("m")
        with d.transact() as txn:
            m.insert(txn, "name", "alice")
        with d.transact() as txn:
            m.insert(txn, "age", 31)
        with d.transact() as txn:
            m.insert(txn, "raw", b"\x01\x02")
        with d.transact() as txn:
            m.insert(txn, "flags", [True, None, 2.5, "s"])
        with d.transact() as txn:
            m.insert(txn, "nested", MapPrelim({"x": "y"}))

    docs, state, enc = build_device_docs([fn], root="m")
    remote = np.zeros((1, 8), dtype=np.int32)
    ship, offsets, deleted = diff_arrays(state, enc, remote)
    out = assert_parity(state, [0], ship, offsets, deleted, enc)
    replica = Doc(client_id=99)
    replica.apply_update_v1(out[0])
    assert replica.get_map("m").to_json() == docs[0].get_map("m").to_json()


@needs_native
def test_rich_text_parity():
    """Format marks + embeds (host content blobs)."""

    def fn(d):
        t = d.get_text("text")
        with d.transact() as txn:
            t.insert(txn, 0, "plain ")
        with d.transact() as txn:
            t.insert_with_attributes(txn, 6, "bold", {"b": True})
        with d.transact() as txn:
            t.insert_embed(txn, 4, {"img": "x.png"})

    docs, state, enc = build_device_docs([fn])
    remote = np.zeros((1, 8), dtype=np.int32)
    ship, offsets, deleted = diff_arrays(state, enc, remote)
    out = assert_parity(state, [0], ship, offsets, deleted, enc)
    replica = Doc(client_id=99)
    replica.apply_update_v1(out[0])
    assert replica.get_text("text").diff() == docs[0].get_text("text").diff()


@needs_native
def test_wire_ref_parity_fast_lane():
    """Rows ingested via the raw-bytes lane carry chunked (<= -2) refs into
    the retained wire bytes; the native finisher re-emits their spans."""
    from ytpu.models.ingest import BatchIngestor

    doc = Doc(client_id=5)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    t = doc.get_text("text")
    with doc.transact() as txn:
        t.insert(txn, 0, "chunky")
    with doc.transact() as txn:
        t.insert(txn, 6, " refs 🙂π")
    with doc.transact() as txn:
        t.remove_range(txn, 2, 3)

    ing = BatchIngestor(n_docs=2, capacity=128)
    for p in log:
        ing.apply_bytes([p, p])
    assert ing.fast_docs == 2 * len(log)

    n_clients = max(8, len(ing.enc.interner))
    import jax.numpy as jnp

    for cut in (0, 3, 8):  # 8 lands mid-emoji in the second block
        remote = np.zeros((2, n_clients), dtype=np.int32)
        cidx = ing.enc.interner.to_idx[5]
        remote[1, cidx] = cut
        ship, offsets, _sv, deleted = map(
            np.asarray,
            encode_diff_batch(ing.state, jnp.asarray(remote), n_clients),
        )
        out = assert_parity(
            state=ing.state,
            docs_idx=[0, 1],
            ship=ship,
            offsets=offsets,
            deleted=deleted,
            enc=ing.enc,
            payloads=ing.payloads,
        )
        fresh = Doc(client_id=77)
        fresh.apply_update_v1(out[0])
        assert fresh.get_text("text").get_string() == t.get_string()


@needs_native
def test_wire_any_canonicalization_parity():
    """A hand-crafted update carrying non-canonical Any encodings (FLOAT32
    2.0, BIGINT 5 — both inside the INTEGER-safe range) must re-encode
    through the diff path exactly like Python's read_any → write_any round
    trip, whichever lane decoded it (VERDICT r3 review finding #2)."""
    import struct

    from ytpu.encoding.lib0 import Writer
    from ytpu.models.ingest import BatchIngestor

    w = Writer()
    w.write_var_uint(1)  # clients
    w.write_var_uint(1)  # blocks
    w.write_var_uint(99)  # client id
    w.write_var_uint(0)  # start clock
    w.write_u8(8)  # info: CONTENT_ANY, no origins, no parent_sub
    w.write_var_uint(1)  # parent_info: root name
    w.write_string("text")
    w.write_var_uint(3)  # Any count
    w.write_u8(124)  # FLOAT32 tag
    w.write_raw(struct.pack(">f", 2.0))  # canonical form would be INTEGER
    w.write_u8(122)  # BIGINT tag
    w.write_raw(struct.pack(">q", 5))  # canonical form would be INTEGER
    w.write_u8(124)  # FLOAT32 tag
    w.write_raw(struct.pack(">f", 2.5))  # stays FLOAT32
    w.write_var_uint(0)  # empty delete set
    payload = w.to_bytes()

    # sanity: the host oracle accepts it
    oracle = Doc(client_id=1)
    oracle.apply_update_v1(payload)

    ing = BatchIngestor(n_docs=1, capacity=64)
    ing.apply_bytes([payload])
    assert int(np.asarray(ing.state.error).max()) == 0

    import jax.numpy as jnp

    n_clients = max(8, len(ing.enc.interner))
    remote = np.zeros((1, n_clients), dtype=np.int32)
    ship, offsets, _sv, deleted = map(
        np.asarray,
        encode_diff_batch(ing.state, jnp.asarray(remote), n_clients),
    )
    out = assert_parity(
        ing.state, [0], ship, offsets, deleted, ing.enc, ing.payloads
    )
    # canonicalized payload still replays
    fresh = Doc(client_id=2)
    fresh.apply_update_v1(out[0])
    assert fresh.state_vector().get(99) == 3


@needs_native
def test_multi_client_ordering_parity():
    """Concurrent edits from several clients: per-update client sections
    must come out sorted by real client id descending, clocks ascending."""
    d1 = Doc(client_id=3)
    d2 = Doc(client_id=200)
    d3 = Doc(client_id=77)
    t1 = d1.get_text("text")
    with d1.transact() as txn:
        t1.insert(txn, 0, "base")
    for d in (d2, d3):
        d.apply_update_v1(d1.encode_state_as_update_v1(StateVector()))
    with d2.transact() as txn:
        d2.get_text("text").insert(txn, 2, "X")
    with d3.transact() as txn:
        d3.get_text("text").insert(txn, 2, "Y")
    for d in (d2, d3):
        d1.apply_update_v1(d.encode_state_as_update_v1(d1.state_vector()))

    merged = d1.encode_state_as_update_v1(StateVector())
    enc = BatchEncoder()
    state = init_state(1, 128)
    batch = enc.build_batch([Update.decode_v1(merged)], n_rows=12, n_dels=4)
    state = apply_update_batch(state, batch, enc.interner.rank_table())
    assert int(np.asarray(state.error).max()) == 0

    remote = np.zeros((1, 8), dtype=np.int32)
    ship, offsets, deleted = diff_arrays(state, enc, remote)
    out = assert_parity(state, [0], ship, offsets, deleted, enc)
    replica = Doc(client_id=99)
    replica.apply_update_v1(out[0])
    assert (
        replica.get_text("text").get_string() == t1.get_string()
    )
