"""Metrics + tracing + phase-timer subsystem (SURVEY §5.1/§5.5)."""

import json
import os
import re
import subprocess
import sys

import pytest

from ytpu.utils import MetricsRegistry, Tracer


def test_counter_and_histogram():
    reg = MetricsRegistry()
    c = reg.counter("ops")
    c.inc()
    c.inc(4)
    assert c.value == 5

    h = reg.histogram("lat")
    for ms in [1, 1, 2, 2, 3, 100]:
        h.observe(ms / 1000)
    assert h.count == 6
    assert 0.0005 < h.p50_s < 0.01
    assert h.p99_s >= 0.05  # dominated by the 100ms outlier
    snap = reg.snapshot()
    assert snap["ops"] == 5
    assert snap["lat.count"] == 6


def test_histogram_timer():
    reg = MetricsRegistry()
    h = reg.histogram("t")
    with h.time():
        pass
    assert h.count == 1
    assert h.p99_s < 0.1


def test_tracer_chrome_export(tmp_path):
    tr = Tracer(enabled=True)
    with tr.span("decode", n=3):
        with tr.span("inner"):
            pass
    payload = json.loads(tr.export_chrome_trace())
    names = [e["name"] for e in payload["traceEvents"]]
    assert names == ["inner", "decode"]  # completion order
    assert payload["traceEvents"][1]["args"] == {"n": 3}

    path = tmp_path / "trace.json"
    tr.export_chrome_trace(str(path))
    assert json.loads(path.read_text())["traceEvents"]


def test_tracer_disabled_is_noop():
    tr = Tracer(enabled=False)
    with tr.span("x"):
        pass
    assert json.loads(tr.export_chrome_trace())["traceEvents"] == []


def test_server_records_apply_metrics():
    from ytpu.core import Doc
    from ytpu.sync.server import SyncServer
    from ytpu.sync.protocol import Message, SyncMessage
    from ytpu.utils import metrics

    metrics.reset()
    server = SyncServer()
    s1, _hello = server.connect("room")
    peer = Doc(client_id=7)
    with peer.transact() as txn:
        peer.get_text("t").insert(txn, 0, "hi")
    update = peer.encode_state_as_update_v1()
    server.receive(s1, Message.sync(SyncMessage.update(update)).encode_v1())

    snap = metrics.snapshot()
    assert snap["sync.updates_applied"] == 1
    assert snap["sync.apply_update.count"] == 1
    assert snap["sync.apply_update.p99_s"] > 0
    assert snap['sync.tenant_updates_applied{tenant="room"}'] == 1
    assert snap["sync.sessions"] == 1
    assert server.doc("room").get_text("t").get_string() == "hi"


# --- labeled metrics + gauges + Prometheus exposition -----------------------


def test_labeled_counter_children():
    reg = MetricsRegistry()
    fam = reg.counter("req", labelnames=("tenant",))
    fam.labels("a").inc()
    fam.labels("a").inc(2)
    fam.labels(tenant="b").inc()
    assert fam.labels("a") is fam.labels("a")  # children are cached
    snap = reg.snapshot()
    assert snap['req{tenant="a"}'] == 3
    assert snap['req{tenant="b"}'] == 1
    # a labeled family refuses direct value ops
    with pytest.raises(ValueError):
        fam.inc()
    # re-registering under a different schema is a conflict
    with pytest.raises(ValueError):
        reg.gauge("req")
    with pytest.raises(ValueError):
        reg.counter("req", labelnames=("other",))


def test_gauge_set_inc_dec_and_max():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3
    g.set_max(10)
    g.set_max(7)  # ratchet: lower values don't regress the mark
    assert g.value == 10
    lg = reg.gauge("slots", labelnames=("pool",))
    lg.labels("x").set(5)
    assert reg.snapshot()['slots{pool="x"}'] == 5


_PROM_LINE = re.compile(
    r"^(?:"
    r"# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ?.*"
    r"|"
    r"[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(?:\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r" [0-9.eE+-]+(?:[0-9.eE+-]*)?"
    r")$"
)


def test_prometheus_text_round_trips_format_validity():
    reg = MetricsRegistry()
    reg.counter("ops.total").inc(7)
    reg.gauge("queue.depth").set(3)
    fam = reg.counter("tenant.ops", labelnames=("tenant",))
    fam.labels('we"ird\\name').inc()
    h = reg.histogram("lat")
    for ms in (1, 2, 5, 80):
        h.observe(ms / 1000)
    text = reg.prometheus_text()
    lines = text.strip().splitlines()
    assert lines, "empty exposition"
    for ln in lines:
        assert _PROM_LINE.match(ln), f"invalid exposition line: {ln!r}"
    # TYPE headers name the SAMPLE family (counters sample as _total,
    # so the header declares the _total name — prometheus_client parity)
    assert "# TYPE ops_total_total counter" in text
    assert "ops_total_total 7" in text
    assert "# TYPE tenant_ops_total counter" in text
    assert "# TYPE queue_depth gauge" in text
    assert "# TYPE lat histogram" in text
    # histogram contract: cumulative buckets, +Inf == _count, sum in s
    buckets = [
        float(ln.rsplit(" ", 1)[1])
        for ln in lines
        if ln.startswith("lat_bucket")
    ]
    assert buckets == sorted(buckets), "bucket series must be cumulative"
    inf_line = [ln for ln in lines if 'le="+Inf"' in ln]
    assert len(inf_line) == 1 and inf_line[0].endswith(" 4")
    count_line = [ln for ln in lines if ln.startswith("lat_count")][0]
    assert count_line.endswith(" 4")
    sum_line = [ln for ln in lines if ln.startswith("lat_sum")][0]
    assert abs(float(sum_line.rsplit(" ", 1)[1]) - 0.088) < 1e-6
    # escaped label values survive
    assert 'tenant="we\\"ird\\\\name"' in text


def test_histogram_labeled_children():
    reg = MetricsRegistry()
    fam = reg.histogram("apply", labelnames=("lane",))
    fam.labels("fast").observe(0.002)
    fam.labels("fast").observe(0.004)
    fam.labels("slow").observe(0.1)
    snap = reg.snapshot()
    assert snap['apply.count{lane="fast"}'] == 2
    assert snap['apply.count{lane="slow"}'] == 1
    assert snap['apply.p99_s{lane="slow"}'] >= 0.05


# --- label handling: escaping + name validation (ISSUE-11 satellite) --------


def test_label_value_escaping_survives_hostile_tenant_names():
    """Regression pin: label VALUES containing backslashes, quotes and
    real newlines must escape into single, spec-valid exposition lines —
    reachable now that tenant ids ride labels on the live `/metrics`
    endpoint."""
    reg = MetricsRegistry()
    fam = reg.counter("tenant.ops", labelnames=("tenant",))
    hostile = 'room"1\\end\nnext'
    fam.labels(hostile).inc(2)
    text = reg.prometheus_text()
    lines = text.strip().splitlines()
    # the newline did NOT split the sample line
    sample = [ln for ln in lines if ln.startswith("tenant_ops_total{")]
    assert len(sample) == 1, lines
    assert sample[0] == (
        'tenant_ops_total{tenant="room\\"1\\\\end\\nnext"} 2'
    )
    for ln in lines:
        assert _PROM_LINE.match(ln), f"invalid exposition line: {ln!r}"
    # the JSON snapshot escapes identically (one shared escaper)
    key = 'tenant.ops{tenant="room\\"1\\\\end\\nnext"}'
    assert reg.snapshot()[key] == 2


def test_label_name_with_trailing_newline_is_rejected():
    """`$` matches before a trailing newline, so "tenant\\n" used to
    validate as a label NAME and emit a torn exposition line; the
    validator now anchors with \\Z."""
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="invalid label name"):
        reg.counter("bad.family", labelnames=("tenant\n",))
    with pytest.raises(ValueError, match="invalid label name"):
        reg.gauge("bad.family2", labelnames=("with space",))


# --- SLO windows: max/p999 + window reset (ISSUE-11 satellite) ---------------


def test_slo_report_carries_p999_and_max():
    from ytpu.utils import HistogramWindow, slo_report

    reg = MetricsRegistry()
    h = reg.histogram("lat")
    w = HistogramWindow(h)
    for ms in [1.0] * 997 + [40.0, 40.0, 900.0]:
        h.observe(ms / 1000)
    rep = slo_report(w, prefix="apply_")
    assert rep["apply_count"] == 1000
    # p999 must NOT collapse into the p99 key (int(99.9) == 99 bug shape)
    assert "apply_p999_ms" in rep and "apply_p99_ms" in rep
    assert rep["apply_p99_ms"] < rep["apply_p999_ms"] <= rep["apply_max_ms"]
    # the 40/900ms outliers are invisible at p99 (the 990th sample is
    # still a 1ms one) but own p999/max — the tail surface the two-tier
    # scan work regresses against
    assert rep["apply_p99_ms"] < 10
    assert rep["apply_p999_ms"] >= 30
    assert rep["apply_max_ms"] >= 900
    assert rep["apply_max_ms_adj"] <= rep["apply_max_ms"]
    # windowed max is bucket-resolution and empty-safe
    assert HistogramWindow(h).max_s == 0.0


def test_histogram_window_reset_between_soak_rounds():
    """Pin the window-reset contract: a window opened AFTER round 1
    scores only round 2's samples — a stale window would silently blend
    both rounds' percentiles (the drift the soak driver guards against
    by re-opening windows per run)."""
    from ytpu.utils import HistogramWindow, slo_report

    reg = MetricsRegistry()
    h = reg.histogram("lat")
    # round 1: slow regime
    for _ in range(50):
        h.observe(0.200)
    stale = HistogramWindow(h)  # opened at the boundary
    r1 = slo_report(HistogramWindow(h), prefix="r1_")
    assert r1["r1_count"] == 0  # fresh window sees nothing yet
    # round 2: fast regime
    for _ in range(50):
        h.observe(0.001)
    fresh = slo_report(stale, prefix="r2_")
    assert fresh["r2_count"] == 50
    # only round 2's regime: p99 AND max stay ~1ms, nowhere near 200ms
    assert fresh["r2_p99_ms"] < 50
    assert fresh["r2_max_ms"] < 50
    # the cumulative histogram would have blended (its p50 spans rounds)
    assert h.count == 100


def test_soak_driver_windows_do_not_blend_across_runs():
    """The driver-level version of the reset pin: two back-to-back
    `SoakDriver.run()`s on one process share the process-global
    histograms, but each report windows ONLY its own run."""
    pytest.importorskip("jax")
    from ytpu.serving import Scenario, ScenarioConfig, SoakDriver
    from ytpu.sync.server import SyncServer

    cfg = ScenarioConfig(
        n_tenants=2, n_sessions=3, events_per_session=5, seed=23
    )
    r1 = SoakDriver(SyncServer(), Scenario(cfg), flush_every=4).run()
    r2 = SoakDriver(SyncServer(), Scenario(cfg), flush_every=4).run()
    # same deterministic scenario, fresh window: the second run's counts
    # equal the first's instead of doubling (a stale window would show
    # run1+run2 samples in run 2's report)
    assert r2["apply_e2e_count"] == r1["apply_e2e_count"] > 0


# --- flight recorder: bounded ring + error dump -----------------------------


def test_tracer_ring_buffer_evicts_oldest():
    tr = Tracer(enabled=True, max_events=4)
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    payload = json.loads(tr.export_chrome_trace())
    names = [e["name"] for e in payload["traceEvents"]]
    assert names == ["s6", "s7", "s8", "s9"]  # drop-oldest, bounded
    assert len(tr) == 4


def test_tracer_instant_events_ride_the_ring():
    tr = Tracer(enabled=True, max_events=8)
    tr.instant("marker", stage="probe")
    payload = json.loads(tr.export_chrome_trace())
    (ev,) = payload["traceEvents"]
    assert ev["ph"] == "i" and ev["args"] == {"stage": "probe"}


def test_dump_on_error_writes_loadable_chrome_trace(tmp_path):
    tr = Tracer(enabled=True, max_events=16)
    with tr.span("decode"):
        pass
    path = str(tmp_path / "crash.json")
    got = tr.dump_on_error(path, error=RuntimeError("kernel abort"))
    assert got == path
    data = json.loads(open(path).read())
    names = [e["name"] for e in data["traceEvents"]]
    assert names == ["decode", "error"]
    err = data["traceEvents"][-1]
    assert err["args"]["type"] == "RuntimeError"
    assert "kernel abort" in err["args"]["message"]


def test_dump_on_error_resolves_path_from_env(tmp_path, monkeypatch):
    tr = Tracer(enabled=False, max_events=16)  # never enabled: still dumps
    template = str(tmp_path / "t-%p.json")
    monkeypatch.setenv("YTPU_TRACE", template)
    got = tr.dump_on_error(error=ValueError("x"))
    assert got == template.replace("%p", str(os.getpid()))
    assert json.loads(open(got).read())["traceEvents"]
    assert tr.enabled is False  # the dump didn't leave tracing on
    monkeypatch.delenv("YTPU_TRACE")
    assert tr.dump_on_error(error=ValueError("x")) is None


def test_tracer_disabled_span_is_shared_noop():
    tr = Tracer(enabled=False)
    a = tr.span("x", big="arg")
    b = tr.span("y")
    assert a is b  # singleton: no per-call allocation when disabled


# --- device-phase timers ----------------------------------------------------


def test_phase_recorder_compile_vs_execute_attribution():
    from ytpu.utils import PhaseRecorder

    rec = PhaseRecorder(enabled=True)
    with rec.span("stage", key=("shape", 1)):
        pass
    with rec.span("stage", key=("shape", 1)):
        pass
    with rec.span("stage", key=("shape", 2)):  # new compiled key
        pass
    with rec.span("hostonly"):  # key=None: execute-only stage
        pass
    rec.transfer("stage", 100, "h2d")
    rec.transfer("stage", 40, "d2h")
    snap = rec.snapshot()
    st = snap["stage"]
    assert st["calls"] == 3 and st["compile_calls"] == 2
    assert st["h2d_bytes"] == 100 and st["d2h_bytes"] == 40
    assert st["transfer_bytes"] == 140
    assert snap["hostonly"]["compile_calls"] == 0
    # disabled: the shared no-op context, zero recording
    rec2 = PhaseRecorder(enabled=False)
    assert rec2.span("s") is rec2.span("t")
    rec2.transfer("s", 10)
    assert rec2.snapshot() == {}


def test_instrumented_ingest_integrate_records_phase_spans():
    """The ingest→integrate path must attribute first-call compile vs
    steady-state execute at the jit boundary, using the cheap
    (n_docs=2, capacity=256) device shapes tier-1 already compiles."""
    pytest.importorskip("jax")
    from ytpu.core import Doc
    from ytpu.models.ingest import BatchIngestor
    from ytpu.utils import phases

    doc = Doc(client_id=3)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    t = doc.get_text("text")
    for i, word in enumerate(["hi ", "there ", "friend"]):
        with doc.transact() as txn:
            t.insert(txn, len(t.get_string()), word)

    phases.reset()
    phases.enable()
    try:
        ing = BatchIngestor(2, 256)
        for p in log:
            ing.apply_bytes([p, None])
    finally:
        phases.disable()
    snap = phases.snapshot()
    st = snap["integrate.xla_batch"]
    assert st["calls"] == len(log)
    # same (state, batch) shapes each step: exactly one first-call
    # compile charge, the rest land in the execute bucket
    assert st["compile_calls"] == 1
    assert st["calls"] - st["compile_calls"] == len(log) - 1
    assert st["compile_s"] > 0 and st["execute_s"] > 0
    assert "ingest.plan" in snap and snap["ingest.plan"]["calls"] == len(log)
    if ing.fast_docs:  # native lane present: wire bytes were counted
        assert snap["decode.v1"]["h2d_bytes"] > 0
        assert snap["ingest.fast_lane"]["h2d_bytes"] > 0
    phases.reset()


def test_ingest_metrics_counters_mirror_lane_stats():
    pytest.importorskip("jax")
    from ytpu.core import Doc
    from ytpu.models.ingest import BatchIngestor
    from ytpu.utils import metrics

    metrics.reset()
    doc = Doc(client_id=9)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    with doc.transact() as txn:
        doc.get_text("text").insert(txn, 0, "m")
    ing = BatchIngestor(2, 256)
    ing.apply_bytes([log[0], None])
    snap = metrics.snapshot()
    assert snap["ingest.fast_docs"] + snap["ingest.slow_docs"] == 1
    assert snap["ingest.fast_docs"] == ing.fast_docs
    assert snap["ingest.slow_docs"] == ing.slow_docs


# --- bench exporter smoke (CI guard; excluded from the tier-1 gate) ---------


@pytest.mark.slow
def test_bench_dry_run_emits_phases_and_metrics():
    """`bench.py --dry-run` is host-only (no jax, no device child) and
    must print exactly one JSON line carrying the `phases` + `metrics`
    keys — the exporter-regression guard before a real bench round."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, YTPU_BENCH_DRY_OPS="120", JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "--dry-run"],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=root,
        env=env,
    )
    assert res.returncode == 0, res.stderr[-800:]
    lines = [ln for ln in res.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["dry_run"] is True
    assert "value" in out and out["host_oracle_updates_per_sec"] > 0
    ph = out["phases"]
    assert "host.replay" in ph
    for st in ph.values():
        for k in ("compile_s", "execute_s", "transfer_bytes", "calls"):
            assert k in st
    assert isinstance(out["metrics"], dict)
