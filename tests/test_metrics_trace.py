"""Metrics + tracing subsystem (SURVEY §5.1/§5.5 greenfield additions)."""

import json

from ytpu.utils import MetricsRegistry, Tracer


def test_counter_and_histogram():
    reg = MetricsRegistry()
    c = reg.counter("ops")
    c.inc()
    c.inc(4)
    assert c.value == 5

    h = reg.histogram("lat")
    for ms in [1, 1, 2, 2, 3, 100]:
        h.observe(ms / 1000)
    assert h.count == 6
    assert 0.0005 < h.p50_s < 0.01
    assert h.p99_s >= 0.05  # dominated by the 100ms outlier
    snap = reg.snapshot()
    assert snap["ops"] == 5
    assert snap["lat.count"] == 6


def test_histogram_timer():
    reg = MetricsRegistry()
    h = reg.histogram("t")
    with h.time():
        pass
    assert h.count == 1
    assert h.p99_s < 0.1


def test_tracer_chrome_export(tmp_path):
    tr = Tracer(enabled=True)
    with tr.span("decode", n=3):
        with tr.span("inner"):
            pass
    payload = json.loads(tr.export_chrome_trace())
    names = [e["name"] for e in payload["traceEvents"]]
    assert names == ["inner", "decode"]  # completion order
    assert payload["traceEvents"][1]["args"] == {"n": 3}

    path = tmp_path / "trace.json"
    tr.export_chrome_trace(str(path))
    assert json.loads(path.read_text())["traceEvents"]


def test_tracer_disabled_is_noop():
    tr = Tracer(enabled=False)
    with tr.span("x"):
        pass
    assert json.loads(tr.export_chrome_trace())["traceEvents"] == []


def test_server_records_apply_metrics():
    from ytpu.core import Doc
    from ytpu.sync.server import SyncServer
    from ytpu.sync.protocol import Message, SyncMessage
    from ytpu.utils import metrics

    metrics.reset()
    server = SyncServer()
    s1, _hello = server.connect("room")
    peer = Doc(client_id=7)
    with peer.transact() as txn:
        peer.get_text("t").insert(txn, 0, "hi")
    update = peer.encode_state_as_update_v1()
    server.receive(s1, Message.sync(SyncMessage.update(update)).encode_v1())

    snap = metrics.snapshot()
    assert snap["sync.updates_applied"] == 1
    assert snap["sync.apply_update.count"] == 1
    assert snap["sync.apply_update.p99_s"] > 0
    assert server.doc("room").get_text("t").get_string() == "hi"
