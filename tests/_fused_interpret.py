"""Shared memoized skip for fused-lane interpret-mode tests.

This container's jax interprets a trivial `pallas_call` fine but raises
NotImplementedError on a primitive the fused integrate kernel uses (seed
behavior — docs/known_backend_issues.md §3), so the breakage cannot be
probed cheaply up front: every test that tries pays the full multi-second
kernel trace before the error surfaces.  The failure is environmental
(per jax build, not per shape), so the FIRST failure is remembered and
every later fused interpret test skips instantly — on a jax whose
interpreter can run the kernel, nothing here triggers and the tests run
in full.  Real-hardware parity is covered by the mosaic ladder and
benches/flagship_fused_chunked.py.
"""

import pytest

_unavailable = None


def _raised_inside_jax(e: BaseException) -> bool:
    """True when the raising frame lives in jax itself (the interpreter's
    own NotImplementedError, e.g. jax/_src/state/discharge.py) — a
    NotImplementedError raised from ytpu code is a real failure and must
    not be memoized into an environment-wide skip."""
    tb, last = e.__traceback__, None
    while tb is not None:
        last = tb.tb_frame.f_code.co_filename
        tb = tb.tb_next
    return last is not None and "/jax/" in last.replace("\\", "/")


def run_or_skip(thunk):
    """Call ``thunk()``, SKIPPING (never failing) when interpret-mode
    Pallas cannot run the fused kernel in this jax build."""
    global _unavailable
    if _unavailable is not None:
        pytest.skip(_unavailable)
    try:
        return thunk()
    except NotImplementedError as e:
        if not _raised_inside_jax(e):
            raise
        _unavailable = f"interpret-mode Pallas unavailable in this jax: {e}"
        pytest.skip(_unavailable)
