"""Device-state checkpoint/resume (SURVEY §5.4 TPU-native addition)."""

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    get_string,
    get_tree,
    init_state,
)
from ytpu.models.checkpoint import (
    load_ingestor,
    load_state,
    save_ingestor,
    save_state,
)
from ytpu.models.ingest import BatchIngestor
from ytpu.types.shared import MapPrelim, TextPrelim


def test_state_roundtrip_and_resume(tmp_path):
    doc = Doc(client_id=1)
    with doc.transact() as txn:
        r = doc.get_array("r")
        r.insert(txn, 0, TextPrelim("hello"))
        r.insert(txn, 1, MapPrelim({"v": 1}))
    enc = BatchEncoder(root_name="r")
    state = init_state(2, 128)
    u = Update.decode_v1(doc.encode_state_as_update_v1())
    state = apply_update_batch(state, enc.build_batch([u, u]), enc.interner.rank_table())

    save_state(str(tmp_path / "ckpt"), state, enc)
    state2, enc2 = load_state(str(tmp_path / "ckpt"))
    for d in range(2):
        assert get_tree(state2, d, enc2.payloads, enc2.keys)["seq"] == ["hello", {"v": 1}]

    # resume: apply MORE updates onto the restored state
    with doc.transact() as txn:
        doc.get_array("r").get(0).insert(txn, 5, "!")
    diff = Update.decode_v1(doc.encode_state_as_update_v1())
    state2 = apply_update_batch(
        state2, enc2.build_batch([diff, diff]), enc2.interner.rank_table()
    )
    assert int(state2.error.max()) == 0
    for d in range(2):
        assert get_tree(state2, d, enc2.payloads, enc2.keys)["seq"] == ["hello!", {"v": 1}]


def test_ingestor_roundtrip_with_pending(tmp_path):
    src = Doc(client_id=9)
    payloads = []
    src.observe_update_v1(lambda p, o, t: payloads.append(p))
    with src.transact() as txn:
        src.get_text("text").insert(txn, 0, "base")
    with src.transact() as txn:
        src.get_text("text").insert(txn, 4, "-tail")

    ing = BatchIngestor(n_docs=1, capacity=64)
    ing.apply([payloads[1]])  # dependent first -> pending
    assert ing.pending_update(0) is not None

    save_ingestor(str(tmp_path / "ing"), ing)
    restored = load_ingestor(str(tmp_path / "ing"))
    assert restored.pending_update(0) is not None
    assert get_string(restored.state, 0, restored.enc.payloads) == ""

    restored.apply([payloads[0]])  # stash drains after restore
    assert int(restored.state.error.max()) == 0
    assert restored.pending_update(0) is None
    assert get_string(restored.state, 0, restored.enc.payloads) == "base-tail"


def test_checkpoint_refuses_unknown_format(tmp_path):
    import pickle

    import pytest

    path = tmp_path / "bad"
    path.mkdir()
    with open(path / "host.pkl", "wb") as f:
        pickle.dump({"format": 999}, f)
    with pytest.raises(ValueError):
        load_state(str(path))


def test_periodic_save_to_fixed_path_overwrites(tmp_path):
    doc = Doc(client_id=4)
    enc = BatchEncoder(root_name="text")
    state = init_state(1, 64)
    path = str(tmp_path / "fixed")
    for i in range(3):  # periodic checkpoint loop to one path
        with doc.transact() as txn:
            doc.get_text("text").insert(txn, 0, f"{i}")
        u = Update.decode_v1(doc.encode_state_as_update_v1(StateVector()))
        save_state(path, state, enc)
    state2, enc2 = load_state(path)
    assert get_string(state2, 0, enc2.payloads) == get_string(state, 0, enc.payloads)


from ytpu.core import StateVector  # noqa: E402


def test_device_server_checkpoint_preserves_root_names(tmp_path):
    """A restored device-authoritative pod must keep emitting each tenant's
    wire root name — falling back to the batch default would rename every
    root across a restart (code-review r3)."""
    from ytpu.core import Doc
    from ytpu.core.state_vector import StateVector
    from ytpu.models.checkpoint import load_device_server, save_device_server
    from ytpu.sync.device_server import DeviceSyncServer
    from ytpu.sync.protocol import Message, SyncMessage

    pod = DeviceSyncServer(n_docs=2, capacity=256, device_authoritative=True)
    session, _ = pod.connect_frames("pad")
    c = Doc(client_id=7)
    with c.transact() as txn:
        c.get_text("notes").insert(txn, 0, "persisted")
    upd = c.encode_state_as_update_v1(StateVector({}))
    pod.receive_frames(
        session, Message.sync(SyncMessage.update(upd)).encode_v1()
    )
    pod.flush_device()
    assert pod._root_names == {"pad": "notes"}

    save_device_server(str(tmp_path / "pod"), pod)
    restored = load_device_server(str(tmp_path / "pod"))
    assert restored.device_authoritative
    assert restored._root_names == {"pad": "notes"}
    assert restored.slot_of("pad") == pod.slot_of("pad")

    # a fresh client syncing from the restored pod sees root "notes"
    diff = restored.device_encode_diff("pad", StateVector({}))
    d = Doc(client_id=9)
    d.apply_update_v1(diff)
    assert d.get_text("notes").get_string() == "persisted"
