"""Single-doc and two-peer Text semantics.

Model: reference yrs/src/types/text.rs test module + update exchange tests.
"""

import pytest

from ytpu.core import Doc, StateVector, Update


def exchange(a: Doc, b: Doc) -> None:
    """One full bidirectional sync (model: test_utils.rs:17 exchange_updates)."""
    ua = a.encode_state_as_update_v1(b.state_vector())
    ub = b.encode_state_as_update_v1(a.state_vector())
    b.apply_update_v1(ua)
    a.apply_update_v1(ub)


def test_insert_and_get_string():
    d = Doc(client_id=1)
    txt = d.get_text("t")
    with d.transact() as txn:
        txt.insert(txn, 0, "hello")
        txt.insert(txn, 5, " world")
    assert txt.get_string() == "hello world"
    assert len(txt) == 11


def test_insert_middle_splits_block():
    d = Doc(client_id=1)
    txt = d.get_text("t")
    with d.transact() as txn:
        txt.insert(txn, 0, "helloworld")
    with d.transact() as txn:
        txt.insert(txn, 5, ", ")
    assert txt.get_string() == "hello, world"


def test_remove_range():
    d = Doc(client_id=1)
    txt = d.get_text("t")
    with d.transact() as txn:
        txt.insert(txn, 0, "hello cruel world")
    with d.transact() as txn:
        txt.remove_range(txn, 5, 6)
    assert txt.get_string() == "hello world"
    assert len(txt) == 11


def test_utf16_astral_lengths():
    d = Doc(client_id=1)
    txt = d.get_text("t")
    with d.transact() as txn:
        txt.insert(txn, 0, "a😀b")  # 😀 is 2 UTF-16 units
    assert len(txt) == 4
    with d.transact() as txn:
        txt.insert(txn, 4, "!")
    assert txt.get_string() == "a😀b!"


def test_two_peer_convergence_simple():
    a, b = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a.get_text("t"), b.get_text("t")
    with a.transact() as txn:
        ta.insert(txn, 0, "abc")
    exchange(a, b)
    assert tb.get_string() == "abc"
    with b.transact() as txn:
        tb.insert(txn, 3, "def")
    exchange(a, b)
    assert ta.get_string() == "abcdef"
    assert tb.get_string() == "abcdef"


def test_concurrent_inserts_converge():
    a, b = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a.get_text("t"), b.get_text("t")
    with a.transact() as txn:
        ta.insert(txn, 0, "base")
    exchange(a, b)
    # concurrent edits at the same position
    with a.transact() as txn:
        ta.insert(txn, 4, "A")
    with b.transact() as txn:
        tb.insert(txn, 4, "B")
    exchange(a, b)
    s1, s2 = ta.get_string(), tb.get_string()
    assert s1 == s2
    assert sorted(s1[4:]) == ["A", "B"]
    # YATA ties break toward the lower client id being left
    assert s1 == "baseAB"


def test_concurrent_insert_delete_converge():
    a, b = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a.get_text("t"), b.get_text("t")
    with a.transact() as txn:
        ta.insert(txn, 0, "hello world")
    exchange(a, b)
    with a.transact() as txn:
        ta.remove_range(txn, 0, 6)  # "world"
    with b.transact() as txn:
        tb.insert(txn, 11, "!")
    exchange(a, b)
    assert ta.get_string() == tb.get_string() == "world!"


def test_three_way_convergence():
    docs = [Doc(client_id=i + 1) for i in range(3)]
    texts = [d.get_text("t") for d in docs]
    for i, (d, t) in enumerate(zip(docs, texts)):
        with d.transact() as txn:
            t.insert(txn, 0, f"p{i}:")
    # all-pairs gossip, twice for transitivity
    for _ in range(2):
        for i in range(3):
            for j in range(3):
                if i != j:
                    u = docs[i].encode_state_as_update_v1(docs[j].state_vector())
                    docs[j].apply_update_v1(u)
    strings = [t.get_string() for t in texts]
    assert strings[0] == strings[1] == strings[2]
    assert sorted(strings[0].split(":")[:-1] + [""]) is not None  # sanity


def test_update_roundtrip_through_fresh_doc():
    a = Doc(client_id=1)
    ta = a.get_text("t")
    with a.transact() as txn:
        ta.insert(txn, 0, "persistent state")
    full = a.encode_state_as_update_v1()
    b = Doc(client_id=2)
    b.apply_update_v1(full)
    assert b.get_text("t").get_string() == "persistent state"


def test_out_of_order_updates_go_pending():
    a = Doc(client_id=1)
    ta = a.get_text("t")
    updates = []
    a.observe_update_v1(lambda payload, origin, txn: updates.append(payload))
    with a.transact() as txn:
        ta.insert(txn, 0, "first")
    with a.transact() as txn:
        ta.insert(txn, 5, "second")
    assert len(updates) == 2
    b = Doc(client_id=2)
    # apply out of order: the second update must stash as pending
    b.apply_update_v1(updates[1])
    assert b.get_text("t").get_string() == ""
    assert b.store.pending is not None
    b.apply_update_v1(updates[0])
    assert b.get_text("t").get_string() == "firstsecond"
    assert b.store.pending is None


def test_pending_updates_survive_full_state_encode():
    a = Doc(client_id=1)
    ta = a.get_text("t")
    updates = []
    a.observe_update_v1(lambda payload, origin, txn: updates.append(payload))
    with a.transact() as txn:
        ta.insert(txn, 0, "x")
    with a.transact() as txn:
        ta.insert(txn, 1, "y")
    b = Doc(client_id=2)
    b.apply_update_v1(updates[1])  # pending
    c = Doc(client_id=3)
    c.apply_update_v1(b.encode_state_as_update_v1())
    c.apply_update_v1(updates[0])
    assert c.get_text("t").get_string() == "xy"


def test_deletes_propagate():
    a, b = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a.get_text("t"), b.get_text("t")
    with a.transact() as txn:
        ta.insert(txn, 0, "abcdef")
    exchange(a, b)
    with a.transact() as txn:
        ta.remove_range(txn, 1, 3)
    exchange(a, b)
    assert ta.get_string() == tb.get_string() == "aef"
