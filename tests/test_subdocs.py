"""Sub-documents (model: reference doc.rs:625-678 + subdocs tests)."""

from ytpu.core import Doc, Options


def test_subdoc_insert_and_events():
    parent = Doc(client_id=1)
    events = []
    parent.observe_subdocs(
        lambda txn, added, removed, loaded: events.append(
            (sorted(added), sorted(removed), sorted(loaded))
        )
    )
    arr = parent.get_array("docs")
    child = Doc(client_id=1, guid="child-guid")
    with parent.transact() as txn:
        arr.push_back(txn, child)
    assert events == [(["child-guid"], [], ["child-guid"])]
    assert parent.store.subdocs["child-guid"] is child
    assert child.parent_doc is parent


def test_subdoc_removal_event():
    parent = Doc(client_id=1)
    events = []
    parent.observe_subdocs(
        lambda txn, added, removed, loaded: events.append(
            (sorted(added), sorted(removed))
        )
    )
    arr = parent.get_array("docs")
    child = Doc(client_id=1, guid="gone")
    with parent.transact() as txn:
        arr.push_back(txn, child)
    with parent.transact() as txn:
        arr.remove(txn, 0)
    assert events[-1] == ([], ["gone"])
    assert "gone" not in parent.store.subdocs
    assert child.destroyed


def test_subdoc_guid_syncs_to_peer():
    parent = Doc(client_id=1)
    arr = parent.get_array("docs")
    child = Doc(client_id=1, guid="shared-child", auto_load=True)
    with parent.transact() as txn:
        arr.push_back(txn, child)
    replica = Doc(client_id=2)
    replica.apply_update_v1(parent.encode_state_as_update_v1())
    got = replica.get_array("docs").get(0)
    assert got.guid == "shared-child"
    assert got.options.auto_load
    # should_load is false by default on the receiving side unless auto_load
    assert got.options.should_load
    assert replica.store.subdocs["shared-child"] is got


def test_subdoc_content_is_independent():
    parent = Doc(client_id=1)
    arr = parent.get_array("docs")
    child = Doc(client_id=5, guid="c1")
    with parent.transact() as txn:
        arr.push_back(txn, child)
    # subdoc contents sync through their own update channel
    with child.transact() as txn:
        child.get_text("t").insert(txn, 0, "inner")
    replica_child = Doc(client_id=6)
    replica_child.apply_update_v1(child.encode_state_as_update_v1())
    assert replica_child.get_text("t").get_string() == "inner"
    # parent update does not carry subdoc content
    replica_parent = Doc(client_id=7)
    replica_parent.apply_update_v1(parent.encode_state_as_update_v1())
    inner = replica_parent.get_array("docs").get(0)
    assert inner.get_text("t").get_string() == ""
