"""Device-side V1 update decoding (ytpu/ops/decode_kernel.py).

Oracle: `ytpu.core.Update.decode_v1` — every decoded row/delete-range must
match the host decoder field-for-field (raw client ids), and replaying the
device-decoded stream through the XLA integrate path must reproduce the
host doc byte-for-byte (reference semantics: update.rs:714-749, :433-488).
"""

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.core.block import GCRange, Item, SkipRange
from ytpu.core.content import BLOCK_GC, CONTENT_DELETED, CONTENT_STRING
from ytpu.models.batch_doc import apply_update_stream, get_string, init_state
from ytpu.ops.decode_kernel import (
    FLAG_BIG_CLIENT,
    FLAG_ERRORS,
    FLAG_MALFORMED,
    FLAG_MULTI_CLIENT,
    FLAG_OVERFLOW,
    FLAG_UNSUPPORTED,
    RawPayloadView,
    decode_updates_v1,
    identity_rank,
    pack_updates,
)


def _edit_log(ops, client_id=1):
    """Wire updates from replaying (tag, pos, arg) text ops on a host doc."""
    doc = Doc(client_id=client_id)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text("text")
    for tag, pos, arg in ops:
        with doc.transact() as txn:
            if tag == "i":
                txt.insert(txn, pos, arg)
            else:
                txt.remove_range(txn, pos, arg)
    return log, txt.get_string()


def _expected_rows_dels(payload):
    """Wire-order (client, clock, len, oc, ok, rc, rk, kind, text) rows from
    the host decoder, plus (client, start, end) delete ranges."""
    u = Update.decode_v1(payload)
    rows = []
    for client, blocks in u.blocks.items():
        for carrier in blocks:
            if isinstance(carrier, SkipRange):
                continue
            if isinstance(carrier, GCRange):
                rows.append((client, carrier.id.clock, carrier.len, -1, 0, -1, 0,
                             BLOCK_GC, None))
                continue
            item: Item = carrier
            oc = item.origin.client if item.origin else -1
            ok = item.origin.clock if item.origin else 0
            rc = item.right_origin.client if item.right_origin else -1
            rk = item.right_origin.clock if item.right_origin else 0
            kind = item.content.kind
            text = item.content.text if kind == CONTENT_STRING else None
            rows.append((client, item.id.clock, item.len, oc, ok, rc, rk, kind, text))
    dels = []
    for client, ranges in u.delete_set.clients.items():
        for s, e in ranges:
            dels.append((client, s, e))
    return rows, dels


def _decode(log, U=4, R=8):
    buf, lens = pack_updates(log)
    stream, flags = decode_updates_v1(buf, lens, U, R)
    return buf, stream, np.asarray(flags)


def _check_field_parity(log, U=4, R=8):
    buf, stream, flags = _decode(log, U, R)
    view = RawPayloadView(buf)
    L = buf.shape[1]
    st = {k: np.asarray(v) for k, v in stream._asdict().items()}
    for s, payload in enumerate(log):
        assert flags[s] & FLAG_ERRORS == 0, f"update {s} flagged {flags[s]}"
        rows, dels = _expected_rows_dels(payload)
        got_n = int(st["valid"][s].sum())
        assert got_n == len(rows), (s, got_n, len(rows))
        for i, (client, clock, ln, oc, ok, rc, rk, kind, text) in enumerate(rows):
            assert st["client"][s, i] == client
            assert st["clock"][s, i] == clock
            assert st["length"][s, i] == ln
            assert st["origin_client"][s, i] == oc
            assert st["origin_clock"][s, i] == ok
            assert st["ror_client"][s, i] == rc
            assert st["ror_clock"][s, i] == rk
            assert st["kind"][s, i] == kind
            if text is not None:
                ref = int(st["content_ref"][s, i])
                assert ref // L == s
                assert view.slice_text(ref, 0, ln) == text
        got_d = int(st["del_valid"][s].sum())
        assert got_d == len(dels), (s, got_d, len(dels))
        for i, (client, start, end) in enumerate(dels):
            assert st["del_client"][s, i] == client
            assert st["del_start"][s, i] == start
            assert st["del_end"][s, i] == end
    return buf, stream, flags


def test_insert_delete_field_parity():
    ops = [
        ("i", 0, "hello"),
        ("i", 5, " world"),
        ("i", 3, "xyz"),
        ("d", 2, 4),
        ("i", 0, "A"),
        ("d", 0, 1),
        ("i", 7, "tail"),
    ]
    log, _ = _edit_log(ops)
    _check_field_parity(log)


def test_unicode_utf16_lengths():
    ops = [
        ("i", 0, "héllo"),  # 2-byte
        ("i", 2, "日本語"),  # 3-byte
        ("i", 1, "🙂🙃"),  # 4-byte → surrogate pairs, u16 len 4
        ("d", 1, 3),
    ]
    log, _ = _edit_log(ops)
    buf, stream, flags = _check_field_parity(log)
    # the astral insert must count UTF-16 units (2 per emoji)
    u = Update.decode_v1(log[2])
    (blocks,) = u.blocks.values()
    assert blocks[0].len == 4


def test_end_to_end_replay_matches_host():
    import random

    rng = random.Random(3)
    ops = []
    length = 0
    for _ in range(120):
        if length > 10 and rng.random() < 0.3:
            pos = rng.randint(0, length - 3)
            n = rng.randint(1, 3)
            ops.append(("d", pos, n))
            length -= n
        else:
            word = "".join(rng.choice("abcdefg håπ🙂") for _ in range(rng.randint(1, 6)))
            ops.append(("i", rng.randint(0, length), word))
            length += len(word)
    log, expect = _edit_log(ops)
    buf, stream, flags = _decode(log, U=4, R=8)
    assert (flags & FLAG_ERRORS == 0).all()

    n_docs = 4
    state = init_state(n_docs, 1024)
    state = apply_update_stream(state, stream, identity_rank(256))
    assert int(np.asarray(state.error).max()) == 0
    view = RawPayloadView(buf)
    assert get_string(state, 0, view) == expect
    assert get_string(state, n_docs - 1, view) == expect


def test_merged_update_multi_block():
    """merge_updates produces one update with many blocks per client."""
    from ytpu.core.update import merge_updates_v1

    ops = [("i", 0, "abc"), ("i", 3, "def"), ("i", 2, "XY"), ("d", 1, 2)]
    log, expect = _edit_log(ops)
    merged = merge_updates_v1(log)
    _check_field_parity([merged], U=8, R=8)

    buf, stream, flags = _decode([merged], U=8, R=8)
    state = init_state(2, 256)
    state = apply_update_stream(state, stream, identity_rank(256))
    assert int(np.asarray(state.error).max()) == 0
    assert get_string(state, 0, RawPayloadView(buf)) == expect


def test_multi_client_flagged_informational():
    d1 = Doc(client_id=1)
    d2 = Doc(client_id=2)
    with d1.transact() as txn:
        d1.get_text("text").insert(txn, 0, "aa")
    u1 = d1.encode_state_as_update_v1()
    d2.apply_update_v1(u1)
    with d2.transact() as txn:
        d2.get_text("text").insert(txn, 2, "bb")
    full = d2.encode_state_as_update_v1()

    buf, stream, flags = _decode([full], U=4, R=4)
    assert flags[0] & FLAG_MULTI_CLIENT
    assert flags[0] & FLAG_ERRORS == 0
    rows, _ = _expected_rows_dels(full)
    assert int(np.asarray(stream.valid)[0].sum()) == len(rows)


def test_content_any_scalars_decode_clean():
    """ContentAny scalar lists decode on device (one step per value)."""
    doc = Doc(client_id=5)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    arr = doc.get_array("text")
    with doc.transact() as txn:
        arr.insert_range(txn, 0, [1, 2.5, "three", True, None])
    _, stream, flags = _decode(log, U=4, R=4)
    assert flags[0] & FLAG_ERRORS == 0
    valid = np.asarray(stream.valid)[0]
    assert valid.sum() == 1
    from ytpu.core.content import CONTENT_ANY

    assert int(np.asarray(stream.kind)[0][valid][0]) == CONTENT_ANY
    assert int(np.asarray(stream.length)[0][valid][0]) == 5


def test_recursive_any_flags_unsupported():
    """Nested array/map Any values exceed the one-step-per-value model."""
    doc = Doc(client_id=5)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    arr = doc.get_array("text")
    with doc.transact() as txn:
        arr.insert(txn, 0, {"nested": [1, 2]})
    _, _, flags = _decode(log, U=4, R=4)
    assert flags[0] & FLAG_UNSUPPORTED


def test_map_parent_sub_without_table_flags_unknown_key():
    doc = Doc(client_id=5)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    m = doc.get_map("m")
    with doc.transact() as txn:
        m.insert(txn, "key", "value")
    _, _, flags = _decode(log, U=4, R=4)
    from ytpu.ops.decode_kernel import FLAG_UNKNOWN_KEY

    assert flags[0] & FLAG_UNKNOWN_KEY


def test_map_parent_sub_with_key_table_decodes():
    import jax.numpy as jnp

    from ytpu.ops.decode_kernel import key_hash_host, pack_updates

    doc = Doc(client_id=5)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    m = doc.get_map("m")
    with doc.transact() as txn:
        m.insert(txn, "title", "hello")
    buf, lens = pack_updates(log)
    h = key_hash_host(b"title")
    stream, flags = decode_updates_v1(
        jnp.asarray(buf),
        jnp.asarray(lens),
        4,
        4,
        key_table=(
            jnp.asarray(np.array([h], dtype=np.int32)),
            jnp.asarray(np.array([17], dtype=np.int32)),
        ),
    )
    flags = np.asarray(flags)
    assert flags[0] & FLAG_ERRORS == 0
    valid = np.asarray(stream.valid)[0]
    assert valid.sum() == 1
    assert int(np.asarray(stream.key)[0][valid][0]) == 17


def test_big_client_id_flags():
    doc = Doc(client_id=2**40)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    with doc.transact() as txn:
        doc.get_text("text").insert(txn, 0, "x")
    _, _, flags = _decode(log, U=4, R=4)
    assert flags[0] & FLAG_BIG_CLIENT


def test_truncated_update_flags_malformed():
    log, _ = _edit_log([("i", 0, "hello world")])
    truncated = log[0][: len(log[0]) - 4]
    buf, lens = pack_updates([truncated])
    _, flags = decode_updates_v1(buf, lens, 4, 4)
    assert np.asarray(flags)[0] & FLAG_MALFORMED


def test_row_overflow_flags():
    from ytpu.core.update import merge_updates_v1

    ops = [("i", 0, "a"), ("i", 0, "b"), ("i", 0, "c"), ("i", 0, "d")]
    log, _ = _edit_log(ops)
    merged = merge_updates_v1(log)
    _, _, flags = _decode([merged], U=2, R=2)
    assert flags[0] & FLAG_OVERFLOW


def test_mixed_batch_bad_lane_emits_nothing():
    """A flagged lane's partial rows must be masked out of the stream."""
    good, expect = _edit_log([("i", 0, "ok")])
    doc = Doc(client_id=7)
    bad_log = []
    doc.observe_update_v1(lambda p, o, t: bad_log.append(p))
    with doc.transact() as txn:
        doc.get_map("m").insert(txn, "k", 1)
    log = [good[0], bad_log[0]]
    buf, stream, flags = _decode(log, U=4, R=4)
    assert flags[0] & FLAG_ERRORS == 0
    assert flags[1] & FLAG_ERRORS != 0
    v = np.asarray(stream.valid)
    assert v[0].sum() == 1
    assert v[1].sum() == 0
    assert np.asarray(stream.del_valid)[1].sum() == 0


def test_gc_rows_decode():
    """GC carriers (info byte 0 + len) decode as BLOCK_GC rows."""
    from collections import deque

    from ytpu.core.block import ID
    from ytpu.core.content import ContentString

    gc = GCRange(ID(3, 0), 4)
    item = Item(ID(3, 4), None, ID(3, 3), None, None, "text", None,
                ContentString("tail"))
    u = Update(blocks={3: deque([gc, item])})
    payload = u.encode_v1()
    rows, _ = _expected_rows_dels(payload)
    assert any(r[7] == BLOCK_GC for r in rows)
    _check_field_parity([payload], U=8, R=8)


def test_huge_string_length_varint_flags_malformed():
    """Regression: a string-length varint near 2^31 wrapped the cursor
    advance negative and bypassed the bounds check (flags stayed 0)."""
    from ytpu.encoding.lib0 import Writer

    w = Writer()
    w.write_var_uint(1)  # n_clients
    w.write_var_uint(1)  # n_blocks
    w.write_var_uint(7)  # client
    w.write_var_uint(0)  # clock
    w.write_u8(0x04 | 0x80)  # String content, has-origin
    w.write_var_uint(7)
    w.write_var_uint(0)  # origin id
    w.write_var_uint(2**31 - 16)  # absurd string byte length
    payload = w.to_bytes()
    buf, lens = pack_updates([payload])
    _, flags = decode_updates_v1(buf, lens, 4, 4)
    assert np.asarray(flags)[0] & FLAG_MALFORMED


def test_content_type_nested_types_decode():
    """Nested shared types (ContentType rows) decode on device: a map
    holding a YText and an XmlElement (named branch). WeakRef branches
    stay host-lane (flagged)."""
    from ytpu.core.content import CONTENT_TYPE

    from ytpu.types.shared import TextPrelim, XmlElementPrelim

    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    arr = doc.get_array("root")
    with doc.transact() as txn:
        arr.insert(txn, 0, TextPrelim("nested text"))
    frag = doc.get_xml_fragment("xml")
    with doc.transact() as txn:
        frag.insert(txn, 0, XmlElementPrelim("div"))

    buf, stream, flags = _decode(log, U=6)
    assert (flags & FLAG_ERRORS == 0).all(), flags
    st = {k: np.asarray(v) for k, v in stream._asdict().items()}
    view = RawPayloadView(buf)
    type_rows = [
        (s, u)
        for s in range(len(log))
        for u in range(st["valid"].shape[1])
        if st["valid"][s, u] and st["kind"][s, u] == CONTENT_TYPE
    ]
    assert type_rows, "expected ContentType rows on the device lane"
    branches = [
        view.type_branch(int(st["content_ref"][s, u])) for s, u in type_rows
    ]
    from ytpu.core.branch import TYPE_TEXT, TYPE_XML_ELEMENT

    refs = sorted(b.type_ref for b in branches)
    assert TYPE_TEXT in refs and TYPE_XML_ELEMENT in refs
    named = [b for b in branches if b.type_ref == TYPE_XML_ELEMENT]
    assert named and named[0].type_name == "div"


def test_weak_type_flags_unsupported():
    from ytpu.types.shared import TextPrelim

    doc = Doc(client_id=1)
    t = doc.get_text("src")
    arr = doc.get_array("links")
    with doc.transact() as txn:
        t.insert(txn, 0, "quote me")
    from ytpu.types.weak import quote_range

    log = []
    doc.observe_update_v1(lambda p, o, t_: log.append(p))
    with doc.transact() as txn:
        q = quote_range(t, txn, 1, 4)
        arr.insert(txn, 0, q)
    buf, stream, flags = _decode(log)
    assert (flags & FLAG_UNSUPPORTED != 0).any(), flags


def test_content_move_rows_decode():
    """ContentMove rows (array.move_to) decode on device with full range
    fields — bounds, assocs, priority (moving.rs:189-215 wire layout)."""
    from ytpu.core.content import CONTENT_MOVE

    doc = Doc(client_id=1)
    arr = doc.get_array("a")
    with doc.transact() as txn:
        for i in range(5):
            arr.push_back(txn, i)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    with doc.transact() as txn:
        arr.move_to(txn, 0, 4)
    with doc.transact() as txn:
        arr.move_range_to(txn, 1, 2, 0)

    buf, stream, flags = _decode(log, U=4)
    assert (flags & FLAG_ERRORS == 0).all(), flags
    st = {k: np.asarray(v) for k, v in stream._asdict().items()}
    from ytpu.core import Update as _U

    for s, payload in enumerate(log):
        up = _U.decode_v1(payload)
        want = []
        for client, blocks in sorted(up.blocks.items()):
            for blk in blocks:
                mv = blk.content.move
                want.append(
                    (
                        mv.start.id.client,
                        mv.start.id.clock,
                        mv.start.assoc,
                        mv.end.id.client,
                        mv.end.id.clock,
                        mv.end.assoc,
                        max(mv.priority, 0),
                    )
                )
        got = [
            (
                int(st["mv_sc"][s, u]),
                int(st["mv_sk"][s, u]),
                int(st["mv_sa"][s, u]),
                int(st["mv_ec"][s, u]),
                int(st["mv_ek"][s, u]),
                int(st["mv_ea"][s, u]),
                int(st["mv_prio"][s, u]),
            )
            for u in range(st["valid"].shape[1])
            if st["valid"][s, u] and st["kind"][s, u] == CONTENT_MOVE
        ]
        assert got == want, (s, got, want)


def test_move_stream_rides_fast_lane_end_to_end():
    """An array move stream through BatchIngestor.apply_bytes: device
    decode + XLA integrate + claim recompute render the host-identical
    order."""
    from ytpu.models.ingest import BatchIngestor
    from ytpu.models.batch_doc import get_tree

    doc = Doc(client_id=1)
    arr = doc.get_array("a")
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    with doc.transact() as txn:
        for i in range(6):
            arr.push_back(txn, i)
    with doc.transact() as txn:
        arr.move_to(txn, 0, 5)
    with doc.transact() as txn:
        arr.move_range_to(txn, 1, 2, 0)

    ing = BatchIngestor(1, 256)
    for p in log:
        ing.apply_bytes([p])
    assert ing.fast_docs == len(log), (ing.fast_docs, ing.slow_docs)
    assert int(np.asarray(ing.state.error).max()) == 0
    tree = get_tree(
        ing.state, 0, ing.payloads, ing.enc.keys, interner=ing.enc.interner
    )
    assert tree["seq"] == arr.to_json()


def test_flat_map_any_values_decode_clean():
    """Depth-1 object values ({str: scalar}) decode on device: header +
    per-key + per-scalar-value steps; content refs re-parse on host via
    read_any."""
    doc = Doc(client_id=5)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    arr = doc.get_array("a")
    with doc.transact() as txn:
        arr.insert(txn, 0, {"name": "zed", "age": 7, "tall": True})
    with doc.transact() as txn:
        arr.insert(txn, 1, [1, {"k": None}, "s"])
    buf, stream, flags = _decode(log, U=4, R=4)
    assert (flags & FLAG_ERRORS == 0).all(), flags
    view = RawPayloadView(buf)
    st = {k: np.asarray(v) for k, v in stream._asdict().items()}
    vals0 = view.slice_values(int(st["content_ref"][0, 0]), 0, 1)
    assert vals0 == [{"name": "zed", "age": 7, "tall": True}]
    # a python list inserts as ONE nested Any value (array token whose
    # children include a depth-1 object)
    vals1 = view.slice_values(int(st["content_ref"][1, 0]), 0, 1)
    assert vals1 == [[1, {"k": None}, "s"]]


def test_map_tenant_object_values_ride_fast_lane():
    from ytpu.models.batch_doc import get_tree
    from ytpu.models.ingest import BatchIngestor

    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    m = doc.get_map("root")
    with doc.transact() as txn:
        m.insert(txn, "config", {"theme": "dark", "size": 14})
    ing = BatchIngestor(1, 128)
    for p in log:
        ing.apply_bytes([p])
    assert ing.fast_docs == len(log), (ing.fast_docs, ing.slow_docs)
    tree = get_tree(
        ing.state, 0, ing.payloads, ing.enc.keys, interner=ing.enc.interner
    )
    assert tree["map"]["config"] == {"theme": "dark", "size": 14}
