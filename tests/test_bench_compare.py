"""benches/bench_compare.py (ISSUE-11 satellite): field-by-field bench
capture diffing with per-metric tolerance and directional regression
semantics — the tool that turns "no worse than" from eyeball work into
an exit code. The tool itself is gated here: synthetic captures pin the
direction/tolerance rules, committed BENCH captures pin self-comparison
as a zero diff, and a slow-marked test runs a real `bench.py --dry-run`
and self-compares its output through the CLI entry."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "benches"))

import bench_compare as bc  # noqa: E402


def test_self_compare_is_zero_diff_and_rc0(tmp_path, capsys):
    cap = {
        "value": 1000.0,
        "soak": {"updates_per_s": 50.0, "apply_p99_ms": 3.0},
        "tunnel_queue": ["a", "b"],
    }
    p = tmp_path / "cap.json"
    p.write_text(json.dumps(cap))
    rc = bc.main([str(p), str(p)])
    assert rc == 0
    diff = bc.compare(cap, cap)
    assert diff["regressions"] == diff["improvements"] == diff["changes"] == []
    assert diff["added"] == diff["removed"] == []


def test_directional_regressions_and_tolerance():
    a = {
        "value": 1000.0,
        "overlap_speedup": 2.0,
        "soak": {"apply_p99_ms": 4.0},
        "chunks": 19,
    }
    # throughput -24% = regression; p99 +50% = regression; chunks drift
    # is neutral (reported, never failing)
    b = {
        "value": 760.0,
        "overlap_speedup": 2.0,
        "soak": {"apply_p99_ms": 6.0},
        "chunks": 24,
    }
    diff = bc.compare(a, b)
    keys = {e["key"] for e in diff["regressions"]}
    assert keys == {"value", "soak.apply_p99_ms"}
    assert {e["key"] for e in diff["changes"]} == {"chunks"}
    # a wide-enough per-key tolerance absorbs the latency regression
    diff = bc.compare(a, b, tolerances={"apply_p99_ms": 0.6})
    assert {e["key"] for e in diff["regressions"]} == {"value"}
    # within the default 10% band nothing fires at all
    diff = bc.compare({"value": 100.0}, {"value": 95.0})
    assert not diff["regressions"]


def test_scan_width_and_trip_rows_regress_like_latency():
    """ISSUE-12 satellite: a conflict-scan width-p99 rise or a dispatch-
    trip-count rise is a REGRESSION (like latency); a trip-reduction
    drop regresses like a speedup; tier occupancy only drifts neutral."""
    a = {
        "scan_width_p99": 120,
        "scan_trips_serial": 67000,
        "scan_trips_two_tier": 16000,
        "scan_trip_reduction": 4.2,
        "scan_tier_wide": 400,
    }
    b = {
        "scan_width_p99": 340,  # tail widened: regression
        "scan_trips_serial": 67000,
        "scan_trips_two_tier": 67000,  # compression lost: regression
        "scan_trip_reduction": 1.0,  # factor collapsed: regression
        "scan_tier_wide": 500,  # occupancy shift: neutral drift only
    }
    diff = bc.compare(a, b)
    keys = {e["key"] for e in diff["regressions"]}
    assert keys == {
        "scan_width_p99",
        "scan_trips_two_tier",
        "scan_trip_reduction",
    }, diff
    assert {e["key"] for e in diff["changes"]} == {"scan_tier_wide"}
    # and the inverse direction reports as improvements, never failures
    diff = bc.compare(b, a)
    assert not diff["regressions"], diff


def test_improvements_and_added_removed_fields():
    a = {"value": 100.0, "gone": 1}
    b = {"value": 200.0, "new_key": {"x": 1}}
    diff = bc.compare(a, b)
    assert [e["key"] for e in diff["improvements"]] == ["value"]
    assert diff["added"] == ["new_key.x"]
    assert diff["removed"] == ["gone"]


def test_direction_classification_rules():
    assert bc.classify("value") == "up"
    assert bc.classify("soak.updates_per_s") == "up"
    assert bc.classify("diff_pipeline_speedup") == "up"
    assert bc.classify("soak.apply_p999_ms") == "down"
    assert bc.classify("apply_max_ms") == "down"
    assert bc.classify("scan_width_p99") == "down"
    assert bc.classify("scan_width_p50") == "down"
    assert bc.classify("scan_width_max") == "down"
    # two-tier scan (ISSUE-12): dispatch-trip counts regress when they
    # RISE (like latency), the compression factor when it DROPS (like a
    # speedup), and tier occupancy is reported-neutral workload shape
    assert bc.classify("scan_trips_serial") == "down"
    assert bc.classify("scan_trips_two_tier") == "down"
    assert bc.classify("scan_tiers.p99.scan_trips_two_tier") == "down"
    assert bc.classify("scan_trip_reduction") == "up"
    assert bc.classify("scan_tier_cheap") == "neutral"
    assert bc.classify("scan_tier_wide") == "neutral"
    # federation (ISSUE-13): convergence cost and anti-entropy traffic
    # regress when they RISE; the scripted chaos schedule stays neutral
    assert bc.classify("federation_converge_rounds") == "down"
    assert bc.classify("federation_anti_entropy_bytes") == "down"
    assert bc.classify("federation.converge_rounds") == "down"
    assert bc.classify("federation.anti_entropy_bytes") == "down"
    assert bc.classify("federation.partitions") == "neutral"
    assert bc.classify("federation.commit_mismatches") == "neutral"
    assert bc.classify("federation.updates_per_s") == "up"
    # autopilot (ISSUE-16): on-vs-off deltas score the controller —
    # availability regresses on DROP, the p99 delta on RISE; raw action
    # counts are policy shape, reported-neutral
    assert bc.classify("autopilot_availability_delta") == "up"
    assert bc.classify("autopilot_p99_adj_delta") == "down"
    assert bc.classify("autopilot.p99_adj_delta_ms") == "down"
    assert bc.classify("autopilot_actions") == "neutral"
    assert bc.classify("autopilot.actions_by_policy.maintenance") == "neutral"
    assert bc.classify("phases.replay.stage.execute_s") == "neutral"
    assert bc.classify("chunks") == "neutral"
    # performance observatory (ISSUE-17): retrace counts and cumulative
    # trace seconds regress when they RISE on the same warmed workload —
    # a shape/static-plan leak re-entered the jit boundary
    assert bc.classify("compile_retraces") == "down"
    assert bc.classify("metrics.compile.retraces") == "down"
    assert bc.classify("observatory.clean.retraces") == "down"
    assert bc.classify("metrics.compile.s_total") == "down"
    # ...while the wall-time attribution fractions are a COMPOSITION of
    # the budget, not better/worse — pinned neutral, including the one
    # whose leaf would otherwise substring-match stall_fraction
    assert bc.classify("profile_device_fraction") == "neutral"
    assert bc.classify("profile_stall_fraction") == "neutral"
    assert bc.classify("profile_idle_fraction") == "neutral"
    assert bc.classify("observatory.profile.profile_net_fraction") == "neutral"
    assert bc.classify("profile.fractions_sum") == "neutral"
    # plain stall_fraction (ISSUE-7 staging gauge) keeps its direction
    assert bc.classify("stall_fraction") == "down"
    assert bc.classify("ingest_raw.stall_fraction") == "down"
    # workload-shape counter whose leaf contains "s_total" stays neutral
    assert bc.classify("metrics.integrate.scan_iterations_total") == "neutral"
    # capacity observatory (ISSUE-18): device-memory footprints regress
    # when they RISE; the forecaster's headroom and the doc-axis ceiling
    # regress when they DROP (the ceiling closing in); the configured
    # budget is an input, not an outcome, and the occupancy/fragmentation
    # gauges are workload shape — both reported-neutral
    assert bc.classify("memory_peak_bytes") == "down"
    assert bc.classify("observatory.memory.peak_bytes") == "down"
    assert bc.classify("memory_program_bytes") == "down"
    assert bc.classify("capacity_headroom_fraction") == "up"
    assert bc.classify("capacity.headroom_fraction") == "up"
    assert bc.classify("doc_ceiling") == "up"
    assert bc.classify("doc_ceiling_sweep.doc_ceiling") == "up"
    assert bc.classify("doc_ceiling_sweep.memory_budget_bytes") == "neutral"
    assert bc.classify("capacity.live_rows") == "neutral"
    assert bc.classify("capacity.dead_rows") == "neutral"
    assert bc.classify("capacity.dead_fraction") == "neutral"
    assert bc.classify("capacity.occupancy_fraction") == "neutral"
    # doc-axis sub-batching (ISSUE-20): a narrowed width is the budget
    # closing in mid-replay — regresses on RISE; the width and the
    # scaling ratio are configuration/workload shape, pinned neutral
    # (doc_ceiling keeps its ISSUE-18 up direction on the sub-batch leg)
    assert bc.classify("capacity.subbatch_narrowed") == "down"
    assert bc.classify("metrics.capacity.subbatch_narrowed") == "down"
    assert bc.classify("doc_shard.subbatch_narrowed") == "down"
    assert bc.classify("subbatch_width") == "neutral"
    assert bc.classify("doc_shard.subbatch_width") == "neutral"
    assert bc.classify("phases.subbatch.width.value") == "neutral"
    assert bc.classify("sub_batch_scaling") == "neutral"
    assert bc.classify("doc_shard.sub_batch_scaling.sub_batch_scaling") == "neutral"
    assert bc.classify("doc_ceiling_pr20.doc_ceiling") == "up"


def test_subbatch_families_regress_on_rise():
    """ISSUE-20 satellite: a `capacity.subbatch_narrowed` rise on the
    same workload is a REGRESSION (the budget forced a narrower width);
    subbatch_width / sub_batch_scaling drift is reported-neutral."""
    a = {
        "metrics": {"capacity.subbatch_narrowed": 0},
        "subbatch_width": 512,
        "sub_batch_scaling": 0.9,
    }
    b = {
        "metrics": {"capacity.subbatch_narrowed": 3},  # budget closing in
        "subbatch_width": 128,  # configuration shift: neutral
        "sub_batch_scaling": 0.5,  # overhead floor drift: neutral
    }
    diff = bc.compare(a, b)
    keys = {e["key"] for e in diff["regressions"]}
    assert keys == {"metrics.capacity.subbatch_narrowed"}, diff
    assert {e["key"] for e in diff["changes"]} == {
        "subbatch_width",
        "sub_batch_scaling",
    }, diff


def test_observatory_families_regress_on_rise():
    """ISSUE-17 satellite: a retrace-count or trace-seconds rise is a
    REGRESSION; profile fraction drift is reported-neutral."""
    a = {
        "compile_retraces": 0,
        "metrics": {"compile.s_total": 2.0},
        "profile_device_fraction": 0.4,
        "profile_stall_fraction": 0.05,
    }
    b = {
        "compile_retraces": 3,  # warmed run started retracing: regression
        "metrics": {"compile.s_total": 9.0},  # tracing cost blew up
        "profile_device_fraction": 0.2,  # composition shift: neutral
        "profile_stall_fraction": 0.2,  # neutral (NOT the staging gauge)
    }
    diff = bc.compare(a, b)
    keys = {e["key"] for e in diff["regressions"]}
    assert keys == {"compile_retraces", "metrics.compile.s_total"}, diff
    assert {e["key"] for e in diff["changes"]} == {
        "profile_device_fraction",
        "profile_stall_fraction",
    }
    # the inverse direction is an improvement, never a failure
    diff = bc.compare(b, a)
    assert not diff["regressions"], diff


def test_trend_baseline_folds_best_ever():
    """ISSUE-17: the --trend baseline takes the BEST value per
    directional key across history (max for up, min for down), newest
    value for neutral/non-numeric keys."""
    history = [
        {"value": 100.0, "soak": {"apply_p99_ms": 8.0}, "note": "old"},
        {"value": 300.0, "soak": {"apply_p99_ms": 2.0}, "note": "peak"},
        {"value": 200.0, "soak": {"apply_p99_ms": 5.0}, "note": "new"},
    ]
    base = bc.trend_baseline(history)
    assert base["value"] == 300.0  # best-ever, not last
    assert base["soak.apply_p99_ms"] == 2.0  # best-ever latency floor
    assert base["note"] == "new"  # neutral: newest wins
    # a candidate that beats LAST round but not the best still regresses
    cand = {"value": 250.0, "soak": {"apply_p99_ms": 2.1}, "note": "cand"}
    diff = bc.compare(base, bc.flatten(cand))
    assert {e["key"] for e in diff["regressions"]} == {"value"}, diff


def test_trend_cli_against_synthetic_captures(tmp_path):
    """--trend end to end: committed-round folding is platform-keyed,
    end-of-round artifacts unwrap their `parsed` surface, and the exit
    code carries the verdict."""
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(
            {"rc": 0, "parsed": {"platform": "tpu", "value": 100.0}}
        )
    )
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"platform": "tpu", "value": 500.0})
    )
    # a different platform's round must NOT leak into the tpu baseline
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps({"platform": "cpu", "value": 9999.0})
    )
    tool = os.path.join(ROOT, "benches", "bench_compare.py")

    def run_trend(cand):
        p = tmp_path / "cand.json"
        p.write_text(json.dumps(cand))
        return subprocess.run(
            [
                sys.executable,
                tool,
                "--trend",
                str(p),
                "--captures-dir",
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
        )

    res = run_trend({"platform": "tpu", "value": 200.0})
    assert res.returncode == 1, res.stdout + res.stderr  # < best-ever 500
    assert "REGRESSION" in res.stdout
    res = run_trend({"platform": "tpu", "value": 510.0})
    assert res.returncode == 0, res.stdout + res.stderr  # new best
    res = run_trend({"platform": "gpu", "value": 1.0})
    assert res.returncode == 2, res.stdout + res.stderr  # no history


def test_cli_exit_codes_and_last_line_loading(tmp_path):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text("noise line\n" + json.dumps({"value": 100.0}) + "\n")
    b.write_text(json.dumps({"value": 50.0}))
    tool = os.path.join(ROOT, "benches", "bench_compare.py")
    res = subprocess.run(
        [sys.executable, tool, str(a), str(b)],
        capture_output=True,
        text=True,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "REGRESSION" in res.stdout
    res = subprocess.run(
        [sys.executable, tool, str(a), str(a), "--json"],
        capture_output=True,
        text=True,
    )
    assert res.returncode == 0
    assert json.loads(res.stdout)["regressions"] == []
    res = subprocess.run(
        [sys.executable, tool, str(a), "/nonexistent.json"],
        capture_output=True,
        text=True,
    )
    assert res.returncode == 2


def test_committed_capture_self_compares_clean():
    """The freshest committed TPU capture is a valid input and a fixed
    point of the tool."""
    cap = os.path.join(ROOT, "BENCH_r05_midsession.json")
    if not os.path.exists(cap):
        pytest.skip("no committed capture in this checkout")
    rc = bc.main([cap, cap])
    assert rc == 0


@pytest.mark.slow
def test_dry_run_self_compare_through_cli(tmp_path):
    """Satellite acceptance: a real `bench.py --dry-run` output compared
    against itself through the CLI is a zero diff with exit 0."""
    env = dict(os.environ, YTPU_BENCH_DRY_OPS="120", JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--dry-run"],
        capture_output=True,
        text=True,
        timeout=600,  # the ISSUE-17 observatory leg adds a real ~15s retrace
        cwd=ROOT,
        env=env,
    )
    assert res.returncode == 0, res.stderr[-800:]
    out = tmp_path / "dry.json"
    out.write_text(res.stdout)
    tool = os.path.join(ROOT, "benches", "bench_compare.py")
    cmp_res = subprocess.run(
        [sys.executable, tool, str(out), str(out), "--json"],
        capture_output=True,
        text=True,
    )
    assert cmp_res.returncode == 0, cmp_res.stdout + cmp_res.stderr
    diff = json.loads(cmp_res.stdout)
    assert diff["regressions"] == [] and diff["changes"] == []
