"""Fused Pallas integrate kernel parity vs the XLA path and the host oracle.

Runs in interpreter mode on the CPU test mesh; the real-TPU compilation is
exercised by bench.py.
"""

import random
import string

import jax
import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    ERR_MISSING_DEP,
    BatchEncoder,
    apply_update_stream,
    ensure_root_anchor,
    get_string,
    get_tree,
    init_state,
)
from ytpu.ops.integrate_kernel import apply_update_stream_fused

from _fused_interpret import run_or_skip


def build_stream(ops_fn, n_docs=8, capacity=128, rows=4, dels=4):
    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    ops_fn(doc)
    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), rows, dels) for p in log]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    expect = doc.get_text("text").get_string()
    return stream, rank, enc, expect


def assert_same_state(a, b):
    """a = XLA-lane state (incrementally maintained origin_slot), b =
    fused-lane state (origin_slot recomputed wholesale at unpack)."""
    for name in a.blocks._fields:
        va, vb = np.asarray(getattr(a.blocks, name)), np.asarray(getattr(b.blocks, name))
        if name == "origin_slot":
            # cache contract (batch_doc.BlockCols.origin_slot): anywhere
            # the XLA lane cached a slot, the fused recompute must agree
            # exactly; and the XLA lane may hold -1 ONLY on rows that
            # never sequence-linked (GC carriers, error-flagged docs) —
            # a cache-wipe regression must not pass as "conservative".
            assert np.array_equal(np.where(va >= 0, va, vb), vb), (
                "column origin_slot diverged"
            )
            kind = np.asarray(a.blocks.kind)
            oc = np.asarray(a.blocks.origin_client)
            nb = np.asarray(a.n_blocks)
            err = np.asarray(a.error)
            D, B = va.shape
            active = np.arange(B)[None, :] < nb[:, None]
            from ytpu.core.content import BLOCK_GC

            must_cache = (
                active
                & (oc >= 0)
                & (kind != BLOCK_GC)
                & (err[:, None] == 0)
            )
            assert np.all(va[must_cache] >= 0), (
                "origin_slot cache wiped on linked rows"
            )
            continue
        assert np.array_equal(va, vb), f"column {name} diverged"
    assert np.array_equal(np.asarray(a.start), np.asarray(b.start))
    assert np.array_equal(np.asarray(a.n_blocks), np.asarray(b.n_blocks))
    assert np.array_equal(np.asarray(a.error), np.asarray(b.error))


def run_both(stream, rank, n_docs=8, capacity=128, d_block=4):
    # refresh_cache=True: assert_same_state compares the origin_slot
    # cache column, so opt into the eager rebuild (the default is the
    # lazy stale-marked contract — tests/test_origin_slot.py covers it).
    # The fused (skippable) lane runs FIRST so a skip never pays the
    # XLA lane's per-shape compile.
    fused_state = run_or_skip(lambda: apply_update_stream_fused(
        init_state(n_docs, capacity), stream, rank, d_block=d_block,
        interpret=True, refresh_cache=True,
    ))
    xla_state = apply_update_stream(init_state(n_docs, capacity), stream, rank)
    return xla_state, fused_state


def test_fused_sequential_inserts():
    def ops(doc):
        t = doc.get_text("text")
        for i, chunk in enumerate(["hello ", "world", "!"]):
            with doc.transact() as txn:
                t.insert(txn, len(t), chunk)

    stream, rank, enc, expect = build_stream(ops)
    xla_state, fused_state = run_both(stream, rank)
    assert_same_state(xla_state, fused_state)
    assert get_string(fused_state, 0, enc.payloads) == expect
    assert int(np.asarray(fused_state.error).max()) == 0


def test_fused_random_edit_trace():
    def ops(doc):
        rng = random.Random(9)
        t = doc.get_text("text")
        for _ in range(30):
            with doc.transact() as txn:
                n = len(t)
                if n > 5 and rng.random() < 0.35:
                    pos = rng.randint(0, n - 2)
                    t.remove_range(txn, pos, min(rng.randint(1, 3), n - pos))
                else:
                    word = "".join(
                        rng.choice(string.ascii_lowercase) for _ in range(3)
                    )
                    t.insert(txn, rng.randint(0, n), word)

    stream, rank, enc, expect = build_stream(ops, capacity=256)
    xla_state, fused_state = run_both(stream, rank, capacity=256)
    assert_same_state(xla_state, fused_state)
    assert get_string(fused_state, 0, enc.payloads) == expect
    assert get_string(fused_state, 7, enc.payloads) == expect


def test_fused_concurrent_clients():
    a, b = Doc(client_id=5), Doc(client_id=3)
    la, lb = [], []
    a.observe_update_v1(lambda p, o, t: la.append(p))
    b.observe_update_v1(lambda p, o, t: lb.append(p))
    ta, tb = a.get_text("text"), b.get_text("text")
    with a.transact() as txn:
        ta.insert(txn, 0, "AAA")
    with b.transact() as txn:
        tb.insert(txn, 0, "BB")
    # interleave the two independent (conflicting) streams
    payloads = [la[0], lb[0]]
    host = Doc(client_id=99)
    for p in payloads:
        host.apply_update_v1(p)
    expect = host.get_text("text").get_string()

    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in payloads]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    xla_state, fused_state = run_both(stream, rank)
    assert_same_state(xla_state, fused_state)
    assert get_string(fused_state, 0, enc.payloads) == expect


def test_fused_map_lww_chains():
    """Map rows (parent_sub key chains) now integrate in-VMEM: per-key LWW
    with chain anchoring and previous-winner tombstoning (block.rs:637-659)."""

    def ops(doc):
        m = doc.get_map("m")
        with doc.transact() as txn:
            m.insert(txn, "a", "1")
        with doc.transact() as txn:
            m.insert(txn, "b", "2")
        with doc.transact() as txn:
            m.insert(txn, "a", "3")  # overwrite: previous winner tombstones
        with doc.transact() as txn:
            m.remove(txn, "b")
        with doc.transact() as txn:
            m.insert(txn, "b", "4")

    stream, rank, enc, _ = build_stream(ops)
    xla_state, fused_state = run_both(stream, rank)
    assert_same_state(xla_state, fused_state)
    assert int(np.asarray(fused_state.error).max()) == 0
    from ytpu.models.batch_doc import get_map

    got = get_map(fused_state, 0, enc.payloads, enc.keys)
    assert got == {"a": "3", "b": "4"}


def test_fused_nested_branches():
    """Nested shared types (p_tag == 2 branch-id parents, child-sequence
    heads on ContentType rows) through the fused kernel."""

    def ops(doc):
        from ytpu.types.shared import ArrayPrelim, MapPrelim

        m = doc.get_map("m")
        with doc.transact() as txn:
            m.insert(txn, "list", ArrayPrelim(["x"]))
        with doc.transact() as txn:
            inner = m.get("list")
            inner.push_back(txn, "y")
        with doc.transact() as txn:
            inner = m.get("list")
            inner.insert(txn, 0, "w")
        with doc.transact() as txn:
            m.insert(txn, "meta", MapPrelim({"k": "v"}))

    stream, rank, enc, _ = build_stream(ops)
    xla_state, fused_state = run_both(stream, rank)
    assert_same_state(xla_state, fused_state)
    assert int(np.asarray(fused_state.error).max()) == 0


def test_fused_text_in_deleted_parent_and_formats():
    """Formats (uncountable rows) and writes under a tombstoned nested
    parent (dead-on-arrival, block.rs:751-765) through the fused kernel."""

    def ops(doc):
        from ytpu.types.shared import TextPrelim

        m = doc.get_map("m")
        with doc.transact() as txn:
            m.insert(txn, "t", TextPrelim("ab"))
        with doc.transact() as txn:
            t = m.get("t")
            t.insert_with_attributes(txn, 1, "B", {"bold": True})
        with doc.transact() as txn:
            m.remove(txn, "t")  # tombstone the nested text

    stream, rank, enc, _ = build_stream(ops)
    xla_state, fused_state = run_both(stream, rank)
    assert_same_state(xla_state, fused_state)
    assert int(np.asarray(fused_state.error).max()) == 0


def test_fused_concurrent_map_writes_two_clients():
    """Concurrent same-key writes from two clients: the chain scan + rank
    tie-break must pick the same winner as the XLA path and host oracle."""
    a, b = Doc(client_id=5), Doc(client_id=9)
    log = []
    a.observe_update_v1(lambda p, o, t: log.append(p))
    b.observe_update_v1(lambda p, o, t: log.append(p))
    ma, mb = a.get_map("m"), b.get_map("m")
    with a.transact() as txn:
        ma.insert(txn, "k", "from-a")
    with b.transact() as txn:
        mb.insert(txn, "k", "from-b")
    # exchange so both end converged (higher client id wins: lib.rs:427-430)
    pa, pb = log[0], log[1]
    b.apply_update_v1(pa)
    a.apply_update_v1(pb)
    assert ma.get("k") == mb.get("k")

    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in (pa, pb)]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    xla_state, fused_state = run_both(stream, rank)
    assert_same_state(xla_state, fused_state)
    from ytpu.models.batch_doc import get_map

    expect_val = ma.get("k")
    got = get_map(fused_state, 0, enc.payloads, enc.keys)
    assert got == {"k": expect_val}


def test_fused_multi_root_anchor_rows():
    """Rows parented at a non-primary named root resolve their per-doc
    BLOCK_ROOT_ANCHOR on the fused lane exactly like the XLA path (the
    kernel's in-VMEM (kind, key) anchor scan vs _integrate_row's)."""

    def ops(doc):
        t1 = doc.get_text("text")
        t2 = doc.get_text("title")
        with doc.transact() as txn:
            t1.insert(txn, 0, "body")
        with doc.transact() as txn:
            t2.insert(txn, 0, "head")
        with doc.transact() as txn:
            t2.insert(txn, 4, "!")
            t1.insert(txn, 4, "?")

    stream, rank, enc, _ = build_stream(ops)
    kid = enc.keys.intern("title")

    def seed():
        st = init_state(8, 128)
        for d in range(8):
            st = ensure_root_anchor(st, d, kid)
        return st

    fused_state = run_or_skip(lambda: apply_update_stream_fused(
        seed(), stream, rank, d_block=4, interpret=True, refresh_cache=True
    ))
    xla_state = apply_update_stream(seed(), stream, rank)
    assert_same_state(xla_state, fused_state)
    assert int(np.asarray(fused_state.error).max()) == 0
    assert get_string(fused_state, 0, enc.payloads) == "body?"
    tree = get_tree(fused_state, 7, enc.payloads, enc.keys)
    assert tree["roots"]["title"]["seq"] == list("head!")


def test_fused_missing_anchor_flags_missing_dep():
    """A p_root row whose anchor was never created must set
    ERR_MISSING_DEP on the fused lane too — never silently alias onto
    the primary branch."""

    def ops(doc):
        with doc.transact() as txn:
            doc.get_text("text").insert(txn, 0, "x")
        with doc.transact() as txn:
            doc.get_text("title").insert(txn, 0, "y")

    stream, rank, enc, _ = build_stream(ops)
    fused_state = run_or_skip(lambda: apply_update_stream_fused(
        init_state(4, 64), stream, rank, d_block=4, interpret=True
    ))
    assert (np.asarray(fused_state.error) & ERR_MISSING_DEP).all()
