"""Capacity observatory (ISSUE-18): occupancy/fragmentation ledger,
device-memory attribution, the headroom forecaster, and the typed
`grow.oom` denial.

Early-alphabet-named on purpose: these assertions pin the readout-word
layout (`LEDGER_WORDS` riding `N_READOUT`) and the zero-new-syncs
contract, so they should fail FIRST — before the heavier replay suites
whose drivers depend on the same words.
"""

import json
import urllib.request
from functools import lru_cache

import numpy as np
import pytest

from ytpu.core import Doc
from ytpu.sync.device_server import DeviceSyncServer
from ytpu.sync.protocol import Message, SyncMessage
from ytpu.utils import metrics
from ytpu.utils.capacity import (
    HeadroomForecaster,
    memory_budget_bytes,
    packed_resident_bytes,
)
from ytpu.utils.faults import FaultError, FaultSpec, faults
from ytpu.utils.phases import phases, program_memory


def _push(server, session, peer_doc):
    sv = server.doc(session.tenant).state_vector()
    diff = peer_doc.encode_state_as_update_v1(sv)
    server.receive(session, Message.sync(SyncMessage.update(diff)).encode_v1())


# --- tenant-facing occupancy/fragmentation ledger ---------------------------


def _served_state():
    """Two tenants, one with tombstones: the serving-side ledger's
    acceptance shape. The deletion spans a block boundary so the slot
    holds TWO clock-contiguous tombstoned rows — a shape compaction can
    actually merge (a lone mid-string tombstone is unmergeable)."""
    server = DeviceSyncServer(n_docs=4, capacity=256)
    s_pad, _ = server.connect("pad")
    s_doc, _ = server.connect("docs")
    alice = Doc(client_id=1)
    with alice.transact() as txn:
        alice.get_text("text").insert(txn, 0, "alice writes a lot of text")
    _push(server, s_pad, alice)
    with alice.transact() as txn:
        alice.get_text("text").insert(txn, 26, " and then appends more")
    _push(server, s_pad, alice)
    with alice.transact() as txn:
        alice.get_text("text").remove_range(txn, 20, 12)  # spans both blocks
    _push(server, s_pad, alice)
    bob = Doc(client_id=2)
    with bob.transact() as txn:
        bob.get_text("text").insert(txn, 0, "bob too")
    _push(server, s_doc, bob)
    server.flush_device()
    return server


def test_capacity_ledger_rows_sum_to_capacity():
    """Per tenant: live + dead + free == slot capacity, dead > 0 where
    tombstones exist, and the same numbers ride `/snapshot`'s capacity
    section and the per-tenant gauges."""
    server = _served_state()
    snap = server.capacity_snapshot()
    assert snap["slot_capacity"] == 256
    assert set(snap["tenants"]) == {"pad", "docs"}
    for name, row in snap["tenants"].items():
        assert (
            row["live_rows"] + row["dead_rows"] + row["free_rows"]
            == snap["slot_capacity"]
        ), (name, row)
        assert row["live_rows"] > 0, (name, row)
    assert snap["tenants"]["pad"]["dead_rows"] > 0  # the tombstoned tenant
    assert 0 < snap["tenants"]["pad"]["dead_fraction"] <= 1
    # batch totals are the tenant rows plus unassigned (all-free) slots
    assert snap["live_rows"] == sum(
        r["live_rows"] for r in snap["tenants"].values()
    )
    # the provider surfaces the same section (the /snapshot body)
    assert server._telemetry_provider()["capacity"]["tenants"]["pad"][
        "dead_rows"
    ] == snap["tenants"]["pad"]["dead_rows"]
    # per-tenant gauges landed in the registry
    g = metrics.gauge("capacity.tenant_dead_rows", labelnames=("tenant",))
    assert g.labels(tenant="pad").value == snap["tenants"]["pad"]["dead_rows"]


def test_ingestor_ledger_matches_state_and_compaction_reclaims():
    """`BatchIngestor.capacity_ledger` mirrors `state_capacity_ledger`,
    and compaction strictly reduces the dead fraction (tail tombstones
    are clock-contiguous, so GC actually reclaims them)."""
    from ytpu.models.batch_doc import state_capacity_ledger
    from ytpu.ops.compaction import compact_state

    server = _served_state()
    live, dead, free = server.ingestor.capacity_ledger()
    s_live, s_dead = state_capacity_ledger(server.ingestor.state)
    assert np.array_equal(live, np.asarray(s_live))
    assert np.array_equal(dead, np.asarray(s_dead))
    assert int(dead.sum()) > 0
    compacted = compact_state(server.ingestor.state)
    c_live, c_dead = state_capacity_ledger(compacted)
    assert int(np.asarray(c_dead).sum()) < int(dead.sum())
    dead_frac = dead.sum() / max(int((live + dead).sum()), 1)
    c_dead_frac = int(np.asarray(c_dead).sum()) / max(
        int((np.asarray(c_live) + np.asarray(c_dead)).sum()), 1
    )
    assert c_dead_frac < dead_frac


# --- packed replay: ledger words ride the existing lazy readout -------------


@lru_cache(maxsize=1)
def _replay_workload():
    import bench as _bench
    from ytpu.models.replay import plan_replay

    ops = []
    length = 0
    for _ in range(6):
        for i in range(20):
            ops.append(("i", length, "abcdef"[i % 6]))
            length += 1
        ops.append(("d", length - 18, 18))
        length -= 18
    log, expect = _bench.build_updates(ops)
    return log, expect, plan_replay(log)


def test_ledger_rides_readout_with_zero_new_syncs():
    """The 3 ledger words ride the SAME [N_READOUT] future the driver
    already drains: `replay.readout` d2h attribution stays pinned at 12
    bytes per readout (unchanged since ISSUE-5), the new words charge
    under their own `capacity.ledger` stage at 4*LEDGER_WORDS per
    readout, and the sync count of a plain chunked run is unchanged."""
    from ytpu.models.replay import FusedReplay
    from ytpu.ops.integrate_kernel import LEDGER_WORDS

    log, expect, plan = _replay_workload()
    phases.reset()
    phases.enable()
    try:
        r = FusedReplay(
            n_docs=2, plan=plan, capacity=256, max_capacity=256,
            d_block=2, chunk=16, lane="xla",
        )
        stats = r.run(log)
        snap = phases.snapshot()
    finally:
        phases.disable()
        phases.reset()
    assert r.get_string(0) == expect
    readouts = snap["replay.readout"]["d2h_bytes"] // 12
    assert readouts >= stats.chunks
    assert snap["replay.readout"]["d2h_bytes"] == 12 * readouts
    assert (
        snap["capacity.ledger"]["d2h_bytes"] == 4 * LEDGER_WORDS * readouts
    ), snap["capacity.ledger"]
    # the drained ledger landed in stats and the occupancy gauges
    assert stats.occupied_rows >= 0 and stats.dead_rows >= 0
    assert "capacity.occupied_rows" in snap
    assert snap["capacity.dead_fraction"]["value"] <= 1.0


def test_compact_efficacy_rides_driver_stats():
    """A tombstone-heavy replay that compacts must report reclaimed
    rows and the chunk gap since the previous compaction."""
    from ytpu.models.replay import FusedReplay

    log, expect, plan = _replay_workload()
    r = FusedReplay(
        n_docs=2, plan=plan, capacity=64, max_capacity=64,
        d_block=2, chunk=16, lane="xla",
    )
    stats = r.run(log)
    assert r.get_string(0) == expect
    assert stats.compactions >= 1
    assert stats.reclaimed_rows > 0, stats
    assert stats.occupied_rows + stats.dead_rows <= 2 * 64


# --- headroom forecaster + typed grow.oom denial ----------------------------


def test_forecaster_flags_degraded_before_grow_oom():
    """The acceptance ordering: on an incompressible head-insert log the
    forecaster must flip `degraded` from ledger observations BEFORE the
    armed `grow.oom` moves the `memory.grow_denied` counter."""
    import bench as _bench
    from ytpu.models.replay import FusedReplay, plan_replay
    from ytpu.ops import integrate_kernel as ik

    ops = [("i", 0, "abcdef"[i % 6]) for i in range(120)]
    log, expect = _bench.build_updates(ops)
    plan = plan_replay(log)
    ik.reset_lane_health()
    faults.clear()
    faults.arm("grow.oom")
    try:
        denied0 = metrics.counter("memory.grow_denied").value
        fc = HeadroomForecaster(
            budget_bytes=ik.packed_state_bytes(2, 48), watermark=0.5
        )
        flagged_pre_denial = []
        observe = fc.observe

        def scored(**kw):
            observe(**kw)
            if fc.report()["degraded"]:
                flagged_pre_denial.append(
                    metrics.counter("memory.grow_denied").value == denied0
                )

        fc.observe = scored
        r = FusedReplay(
            n_docs=2, plan=plan, capacity=32, max_capacity=1024,
            d_block=2, chunk=4, lane="xla", forecaster=fc,
        )
        stats = r.run(log)
    finally:
        faults.clear()
        ik.reset_lane_health()
    assert r.get_string(0) == expect
    assert stats.growths >= 1 and stats.recoveries >= 1, stats
    assert metrics.counter("memory.grow_denied").value > denied0
    assert flagged_pre_denial and flagged_pre_denial[0] is True, (
        flagged_pre_denial
    )
    rep = fc.report()
    assert rep["grow_exceeds_budget"] and rep["degraded"]
    assert rep["headroom_fraction"] < 0  # next grow overshoots the budget


def test_grow_oom_error_reports_attempted_vs_available_bytes():
    """The typed denial carries the numbers an operator needs, stays a
    FaultError (site taxonomy), and stays on the checkpoint-resume
    recovery path (`is_device_fault`)."""
    from ytpu.ops.integrate_kernel import (
        GrowOomError,
        is_device_fault,
        packed_state_bytes,
    )

    spec = FaultSpec("grow.oom")
    e = GrowOomError(
        spec,
        capacity=32,
        new_capacity=64,
        n_docs=2,
        attempted_bytes=packed_state_bytes(2, 64),
        available_bytes=10_000,
    )
    assert isinstance(e, FaultError)
    assert is_device_fault(e)
    assert e.attempted_bytes == packed_state_bytes(2, 64)
    assert e.available_bytes == 10_000
    assert str(e.attempted_bytes) in str(e) and "budget" in str(e)
    assert "32 -> 64" in str(e)


def test_memory_budget_env_override(monkeypatch):
    monkeypatch.setenv("YTPU_MEMORY_BUDGET_BYTES", "12345")
    assert memory_budget_bytes() == 12345
    monkeypatch.setenv("YTPU_MEMORY_BUDGET_BYTES", "junk")
    assert memory_budget_bytes() == 16 << 30
    assert packed_resident_bytes(2, 64) > 0


def test_forecaster_report_math():
    """Analytic fallback below 2 samples; fitted model after; the
    degraded flag needs BOTH budget overshoot and an occupancy trend."""
    fc = HeadroomForecaster(budget_bytes=5_000, watermark=0.5)
    assert fc.report() == {
        "observed": 0, "budget_bytes": 5_000, "degraded": False,
    }
    fc.observe(
        n_docs=2, capacity=16, occupied_rows=2, chunks=1, max_capacity=64
    )
    rep = fc.report()
    assert rep["grow_exceeds_budget"]  # analytic: psb(2,32)=6912 > 5k
    assert not rep["degraded"]  # no trend yet (one sample, rate 0)
    fc.observe(
        n_docs=2, capacity=16, occupied_rows=10, chunks=3, max_capacity=64
    )
    rep = fc.report()
    assert rep["growth_rows_per_chunk"] > 0
    assert rep["chunks_to_watermark"] is not None
    assert rep["degraded"]
    # trend projects (watermark_rows - occupied) / rate chunks ahead
    assert rep["chunks_to_watermark"] == pytest.approx(
        (0.5 * 32 - 10) / rep["growth_rows_per_chunk"], rel=1e-3
    )


# --- device-memory attribution at the jit boundary --------------------------


def test_program_memory_attribution_journals_and_peaks():
    """A span carrying a `program_memory` thunk journals the program's
    XLA memory analysis on first sighting and ratchets the per-stage
    peak ledger + gauges."""
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: (x @ x).sum())
    x = jnp.zeros((64, 64), jnp.float32)
    phases.reset()
    phases.enable()
    try:
        with phases.span(
            "integrate.fused",
            ((64, 64),),
            axes=("shape",),
            memory=program_memory(fn, x),
        ):
            fn(x)
        report = phases.memory_report()
    finally:
        phases.disable()
        phases.reset()
    prog = report["programs"]["integrate.fused"]
    assert prog["peak_bytes"] > 0
    kinds = prog["kinds"]
    assert kinds["argument_bytes"] == 64 * 64 * 4
    assert kinds["resident_bytes"] == (
        kinds["argument_bytes"]
        + kinds["output_bytes"]
        - kinds["alias_bytes"]
        + kinds["temp_bytes"]
    )
    assert report["peak_program"] == "integrate.fused"
    assert report["peak_bytes"] == prog["peak_bytes"]
    # the per-program gauges landed in the registry
    g = metrics.gauge(
        "memory.program_bytes", labelnames=("program", "kind")
    )
    assert g.labels(
        program="integrate.fused", kind="argument_bytes"
    ).value == 64 * 64 * 4
    assert metrics.gauge(
        "memory.program_peak_bytes", labelnames=("program",)
    ).labels(program="integrate.fused").value == prog["peak_bytes"]


def test_program_memory_snapshots_specs_before_donation():
    """The thunk must survive being invoked AFTER the donated arrays
    are consumed — specs are captured eagerly at span construction."""
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: x + 1, donate_argnums=(0,))
    x = jnp.zeros((16,), jnp.float32)
    thunk = program_memory(fn, x)
    fn(x)  # donates x's buffer
    kinds = thunk()  # must not touch the deleted buffer
    assert kinds["argument_bytes"] == 16 * 4
    assert kinds["alias_bytes"] == 16 * 4  # donation aliased in-place


# --- /capacity endpoint + health provider -----------------------------------


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, r.read().decode()


def test_capacity_endpoint_serves_forecast_and_degrades_health():
    from ytpu.utils.telemetry import TelemetryServer

    fc = HeadroomForecaster(budget_bytes=5_000, watermark=0.5)
    fc.observe(
        n_docs=2, capacity=16, occupied_rows=4, chunks=1, max_capacity=64
    )
    fc.observe(
        n_docs=2, capacity=16, occupied_rows=12, chunks=3, max_capacity=64
    )
    with TelemetryServer(port=0) as t:
        t.add_capacity_provider("replay", fc.provider())
        t.add_health_provider("capacity", fc.provider())
        status, body = _get(t.port, "/capacity")
        assert status == 200
        cap = json.loads(body)
        assert cap["replay"]["degraded"] is True
        assert cap["replay"]["budget_bytes"] == 5_000
        assert "memory" in cap  # the per-program peak ledger section
        _, hbody = _get(t.port, "/healthz")
        h = json.loads(hbody)
        assert h["status"] == "degraded"
        assert h["capacity"]["grow_exceeds_budget"] is True
    # the endpoint self-accounts its scrapes like its siblings
    assert metrics.counter(
        "telemetry.scrapes", labelnames=("endpoint",)
    ).labels("capacity").value >= 1
