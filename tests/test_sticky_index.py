"""Sticky indices (relative positions).

Model: reference moving.rs StickyIndex tests + ywasm sticky-index tests.
"""

from ytpu.core import Doc
from ytpu.core.moving import ASSOC_AFTER, ASSOC_BEFORE


def test_sticky_index_follows_inserts():
    d = Doc(client_id=1)
    t = d.get_text("t")
    with d.transact() as txn:
        t.insert(txn, 0, "hello world")
    pos = t.sticky_index(6, ASSOC_AFTER)  # before "world"
    with d.transact() as txn:
        t.insert(txn, 0, ">>> ")  # shift everything right by 4
    with d.transact() as txn:
        assert t.sticky_index_offset(txn, pos) == 10
        assert t.get_string()[10:] == "world"


def test_sticky_index_survives_deletion_around():
    d = Doc(client_id=1)
    t = d.get_text("t")
    with d.transact() as txn:
        t.insert(txn, 0, "abcdef")
    pos = t.sticky_index(3, ASSOC_AFTER)  # at "d"
    with d.transact() as txn:
        t.remove_range(txn, 0, 2)  # "cdef"
    with d.transact() as txn:
        assert t.sticky_index_offset(txn, pos) == 1
        assert t.get_string()[1] == "d"


def test_sticky_index_wire_roundtrip_through_move():
    # sticky indices are embedded in Move wire format; check via Move
    from ytpu.core.moving import Move, StickyIndex
    from ytpu.core import ID
    from ytpu.encoding.codec import DecoderV1, EncoderV1

    m = Move(
        StickyIndex.from_id(ID(1, 5), ASSOC_BEFORE),
        StickyIndex.from_id(ID(2, 9), ASSOC_AFTER),
        priority=1,
    )
    enc = EncoderV1()
    m.encode(enc)
    out = Move.decode(DecoderV1(enc.to_bytes()))
    assert out == m


def test_sticky_index_ends():
    d = Doc(client_id=1)
    t = d.get_text("t")
    with d.transact() as txn:
        t.insert(txn, 0, "xyz")
    end = t.sticky_index(3, ASSOC_AFTER)
    begin = t.sticky_index(0, ASSOC_BEFORE)
    with d.transact() as txn:
        t.insert(txn, 3, "!!")
        t.insert(txn, 0, "??")
    with d.transact() as txn:
        assert t.sticky_index_offset(txn, begin) == 0
        # end anchored past the last item sticks to the type end
        assert t.sticky_index_offset(txn, end) == len(t)


def test_sticky_index_across_sync():
    a, b = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a.get_text("t"), b.get_text("t")
    with a.transact() as txn:
        ta.insert(txn, 0, "shared")
    b.apply_update_v1(a.encode_state_as_update_v1())
    pos = ta.sticky_index(3, ASSOC_AFTER)
    # concurrent edit on b shifts the position
    with b.transact() as txn:
        tb.insert(txn, 0, "___")
    a.apply_update_v1(b.encode_state_as_update_v1(a.state_vector()))
    with a.transact() as txn:
        assert ta.sticky_index_offset(txn, pos) == 6
