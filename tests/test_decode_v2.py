"""Device-side V2 update decoding (ytpu/ops/decode_v2.py).

Parity oracle: host `Update.decode_v2` on the same bytes. The device lane
must emit identical block rows / delete ranges for the supported set
(GC / Skip / Deleted / String, root + nested parents, parent_sub keys,
multi-section, delete sets) and flag everything else to the host lane —
VERDICT r2 #5: a V2-encoded B4 stream rides the raw-bytes lane with zero
host fallbacks.
"""

import os
import random
import string as _string

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.core.state_vector import StateVector
from ytpu.ops.decode_kernel import FLAG_ERRORS, FLAG_UNSUPPORTED, utf8_slice_u16
from ytpu.ops.decode_v2 import decode_updates_v2, pack_updates_v2


def v1_to_v2(payload: bytes) -> bytes:
    return Update.decode_v1(payload).encode_v2()


def capture_v1(ops_fn, client_id=1):
    doc = Doc(client_id=client_id)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    ops_fn(doc)
    return doc, log


def decode(payloads_v2, max_rows=8, max_dels=8, **kw):
    buf, lens, spans, side = pack_updates_v2(payloads_v2)
    stream, flags = decode_updates_v2(
        buf, lens, spans, max_rows, max_dels, sidecar=side, **kw
    )
    return buf, stream, np.asarray(flags)


def oracle_rows(payload_v2):
    """(client, clock, length, kind-ish) rows from the host decoder."""
    up = Update.decode_v2(payload_v2)
    rows = []
    for client, blocks in sorted(up.blocks.items()):
        for b in blocks:
            rows.append((client, b.id.clock, b.len))
    return rows


def test_plain_text_inserts_roundtrip():
    def ops(doc):
        t = doc.get_text("text")
        for chunk in ["hello ", "world", "!"]:
            with doc.transact() as txn:
                t.insert(txn, len(t), chunk)

    doc, log = capture_v1(ops)
    v2 = [v1_to_v2(p) for p in log]
    buf, stream, flags = decode(v2)
    assert (flags & FLAG_ERRORS == 0).all(), flags
    valid = np.asarray(stream.valid)
    for s, payload in enumerate(v2):
        got = [
            (
                int(np.asarray(stream.client)[s, u]),
                int(np.asarray(stream.clock)[s, u]),
                int(np.asarray(stream.length)[s, u]),
            )
            for u in range(valid.shape[1])
            if valid[s, u]
        ]
        assert got == oracle_rows(payload), (s, got)
    # string contents slice straight out of the packed buffer
    flat = np.asarray(buf).reshape(-1)
    refs = np.asarray(stream.content_ref)
    texts = []
    for s in range(len(v2)):
        for u in range(valid.shape[1]):
            if valid[s, u] and refs[s, u] >= 0:
                texts.append(
                    utf8_slice_u16(
                        flat,
                        refs[s, u],
                        0,
                        int(np.asarray(stream.length)[s, u]),
                    )
                )
    assert texts == ["hello ", "world", "!"]


def test_deletes_and_delete_set():
    def ops(doc):
        t = doc.get_text("text")
        with doc.transact() as txn:
            t.insert(txn, 0, "abcdefgh")
        with doc.transact() as txn:
            t.remove_range(txn, 2, 3)
        with doc.transact() as txn:
            t.remove_range(txn, 0, 1)

    doc, log = capture_v1(ops)
    v2 = [v1_to_v2(p) for p in log]
    _, stream, flags = decode(v2)
    assert (flags & FLAG_ERRORS == 0).all(), flags
    dvalid = np.asarray(stream.del_valid)
    for s, payload in enumerate(v2):
        up = Update.decode_v2(payload)
        want = []
        for client, ranges in sorted(up.delete_set.clients.items()):
            for a, bnd in ranges:
                want.append((client, a, bnd))
        got = sorted(
            (
                int(np.asarray(stream.del_client)[s, r]),
                int(np.asarray(stream.del_start)[s, r]),
                int(np.asarray(stream.del_end)[s, r]),
            )
            for r in range(dvalid.shape[1])
            if dvalid[s, r]
        )
        assert got == sorted(want), (s, got, want)


def test_merged_multi_client_update_with_skips():
    """Merged updates exercise multi-section wire + Skip runs."""
    from ytpu.compat import merge_updates

    # build two docs whose merged update has 2 client sections + a skip
    d1 = Doc(client_id=1)
    with d1.transact() as txn:
        d1.get_text("text").insert(txn, 0, "aaaa")
    d2 = Doc(client_id=2)
    d2.apply_update_v1(d1.encode_state_as_update_v1(StateVector({})))
    with d2.transact() as txn:
        d2.get_text("text").insert(txn, 2, "bb")
    u_all = d2.encode_state_as_update_v1(StateVector({}))
    # a gapped second update from client 1 (skip synthesized on merge)
    with d1.transact() as txn:
        d1.get_text("text").insert(txn, 0, "x")
    with d1.transact() as txn:
        d1.get_text("text").insert(txn, 0, "y")
    full = d1.encode_state_as_update_v1(StateVector({}))
    merged = merge_updates(u_all, full)
    v2 = [v1_to_v2(merged)]
    _, stream, flags = decode(v2, max_rows=12)
    assert (flags & FLAG_ERRORS == 0).all(), flags
    valid = np.asarray(stream.valid)
    got = [
        (
            int(np.asarray(stream.client)[0, u]),
            int(np.asarray(stream.clock)[0, u]),
            int(np.asarray(stream.length)[0, u]),
        )
        for u in range(valid.shape[1])
        if valid[0, u]
    ]
    # oracle emits items + GC only (skips carry no row)
    up = Update.decode_v2(v2[0])
    want = []
    for client, blocks in sorted(up.blocks.items()):
        for blk in blocks:
            if type(blk).__name__ != "SkipRange":
                want.append((client, blk.id.clock, blk.len))
    assert sorted(got) == sorted(want), (got, want)


def test_map_rows_parent_sub_keys():
    from ytpu.ops.decode_kernel import key_hash_host

    def ops(doc):
        m = doc.get_map("config")
        with doc.transact() as txn:
            m.insert(txn, "title", "zedoc")

    doc, log = capture_v1(ops)
    v2 = [v1_to_v2(p) for p in log]
    # Round 4 widened the lane: ContentAny map values DEVICE-decode. The
    # parent_sub key must resolve through the key table — without one the
    # lane flags FLAG_UNKNOWN_KEY (host fallback interns for next step).
    from ytpu.ops.decode_kernel import FLAG_UNKNOWN_KEY

    _, stream, flags = decode(v2)
    assert (flags & FLAG_UNKNOWN_KEY != 0).all()
    assert not np.asarray(stream.valid).any()
    assert not np.asarray(stream.valid).any()


def test_random_text_trace_parity():
    rng = random.Random(11)

    def ops(doc):
        t = doc.get_text("text")
        for _ in range(40):
            with doc.transact() as txn:
                n = len(t)
                if n > 6 and rng.random() < 0.35:
                    pos = rng.randint(0, n - 3)
                    t.remove_range(txn, pos, rng.randint(1, 3))
                else:
                    word = "".join(
                        rng.choice(_string.ascii_lowercase)
                        for _ in range(rng.randint(1, 8))
                    )
                    t.insert(txn, rng.randint(0, n), word)

    doc, log = capture_v1(ops)
    v2 = [v1_to_v2(p) for p in log]
    _, stream, flags = decode(v2, max_rows=8, max_dels=8)
    assert (flags & FLAG_ERRORS == 0).all(), flags
    valid = np.asarray(stream.valid)
    dvalid = np.asarray(stream.del_valid)
    for s, payload in enumerate(v2):
        up = Update.decode_v2(payload)
        want = []
        for client, blocks in sorted(up.blocks.items()):
            for blk in blocks:
                want.append((client, blk.id.clock, blk.len))
        got = [
            (
                int(np.asarray(stream.client)[s, u]),
                int(np.asarray(stream.clock)[s, u]),
                int(np.asarray(stream.length)[s, u]),
            )
            for u in range(valid.shape[1])
            if valid[s, u]
        ]
        assert sorted(got) == sorted(want), (s, got, want)
        want_d = []
        for client, ranges in sorted(up.delete_set.clients.items()):
            for a, bnd in ranges:
                want_d.append((client, a, bnd))
        got_d = sorted(
            (
                int(np.asarray(stream.del_client)[s, r]),
                int(np.asarray(stream.del_start)[s, r]),
                int(np.asarray(stream.del_end)[s, r]),
            )
            for r in range(dvalid.shape[1])
            if dvalid[s, r]
        )
        assert got_d == sorted(want_d), (s, got_d, want_d)


def test_unicode_string_offsets():
    def ops(doc):
        t = doc.get_text("text")
        with doc.transact() as txn:
            t.insert(txn, 0, "héllo 🌍 wörld")
        with doc.transact() as txn:
            t.insert(txn, 3, "日本語")

    doc, log = capture_v1(ops)
    v2 = [v1_to_v2(p) for p in log]
    buf, stream, flags = decode(v2)
    assert (flags & FLAG_ERRORS == 0).all(), flags
    flat = np.asarray(buf).reshape(-1)
    valid = np.asarray(stream.valid)
    texts = [
        utf8_slice_u16(
            flat,
            int(np.asarray(stream.content_ref)[s, u]),
            0,
            int(np.asarray(stream.length)[s, u]),
        )
        for s in range(len(v2))
        for u in range(valid.shape[1])
        if valid[s, u]
    ]
    assert texts == ["héllo 🌍 wörld", "日本語"]
    # lengths are UTF-16 units (surrogate pair counts 2)
    assert int(np.asarray(stream.length)[0, 0]) == 14


def test_apply_v2_device_stream_end_to_end():
    """A V2 stream decoded on device integrates into the batch engine and
    renders the same text as the host replay — zero host fallbacks."""
    import jax.numpy as jnp

    from ytpu.models.batch_doc import (
        apply_update_stream,
        get_string,
        init_state,
    )
    from ytpu.models.batch_doc import BatchEncoder
    from ytpu.ops.decode_kernel import RawPayloadView, identity_rank

    rng = random.Random(5)

    def ops(doc):
        t = doc.get_text("text")
        for _ in range(25):
            with doc.transact() as txn:
                n = len(t)
                if n > 5 and rng.random() < 0.3:
                    t.remove_range(txn, rng.randint(0, n - 2), 1)
                else:
                    t.insert(
                        txn,
                        rng.randint(0, n),
                        rng.choice(_string.ascii_lowercase) * rng.randint(1, 4),
                    )

    doc, log = capture_v1(ops)
    v2 = [v1_to_v2(p) for p in log]
    buf, lens, spans, side = pack_updates_v2(v2)
    stream, flags = decode_updates_v2(buf, lens, spans, 4, 4, sidecar=side)
    assert (np.asarray(flags) & FLAG_ERRORS == 0).all(), np.asarray(flags)

    # the stream is already step-shaped: update s = step s over the batch
    state = init_state(1, 256)
    state = apply_update_stream(state, stream, identity_rank(2))
    payloads = RawPayloadView(np.asarray(buf))
    assert int(np.asarray(state.error).max()) == 0
    assert get_string(state, 0, payloads) == doc.get_text("text").get_string()


def test_b4_trace_prefix_rides_device_lane():
    """VERDICT r2 #5 'done' criterion: a V2-encoded B4 editing-trace stream
    decodes on the device lane with ZERO host fallbacks, and the decoded
    stream integrates to the same text as the host replay."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    from ytpu.models.batch_doc import apply_update_stream, get_string, init_state
    from ytpu.ops.decode_kernel import RawPayloadView, identity_rank

    if not os.path.exists(bench.TRACE_PATH):
        pytest.skip(f"B4 trace asset not in this container: {bench.TRACE_PATH}")
    ops = bench.load_b4_ops(400)
    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    t = doc.get_text("text")
    for tag, pos, payload in ops:
        with doc.transact() as txn:
            if tag == "i":
                t.insert(txn, pos, payload)
            else:
                t.remove_range(txn, pos, payload)
    v2 = [v1_to_v2(p) for p in log]
    buf, lens, spans, side = pack_updates_v2(v2)
    stream, flags = decode_updates_v2(buf, lens, spans, 4, 4, sidecar=side)
    f = np.asarray(flags)
    assert (f & FLAG_ERRORS == 0).all(), f[(f & FLAG_ERRORS) != 0][:5]

    state = init_state(1, 4096)
    state = apply_update_stream(state, stream, identity_rank(2))
    assert int(np.asarray(state.error).max()) == 0
    got = get_string(state, 0, RawPayloadView(np.asarray(buf)))
    assert got == doc.get_text("text").get_string()


def test_big_client_ids_resolve_through_hash_table():
    """Real Yjs client ids (random 53-bit) ride the V2 lane: the expander
    reconstructs each big id's unsigned-varint bytes from its signed V2
    encoding and hashes with client_hash_host's mixing."""
    import jax.numpy as jnp

    from ytpu.ops.decode_kernel import client_hash_host

    big_a = (1 << 52) + 12345
    big_b = (1 << 45) + 7
    d1 = Doc(client_id=big_a)
    with d1.transact() as txn:
        d1.get_text("t").insert(txn, 0, "from-a")
    d2 = Doc(client_id=big_b)
    d2.apply_update_v1(d1.encode_state_as_update_v1(StateVector({})))
    with d2.transact() as txn:
        d2.get_text("t").insert(txn, 3, "-b-")
    with d2.transact() as txn:
        # a deletion: the DS client id (rest stream) must hash too
        d2.get_text("t").remove_range(txn, 0, 1)
    v2 = [v1_to_v2(d2.encode_state_as_update_v1(StateVector({})))]

    # interner tables: both ids interned; big ones registered in the hash
    # table exactly as BatchIngestor does
    idx = {big_a: 0, big_b: 1}
    hashes = {client_hash_host(c): i for c, i in idx.items()}
    hs = sorted(hashes)
    cht = (
        jnp.asarray(np.asarray(hs, dtype=np.int32)),
        jnp.asarray(np.asarray([hashes[h] for h in hs], dtype=np.int32)),
    )
    client_table = (
        jnp.asarray(np.zeros(0, dtype=np.int64)),
        jnp.asarray(np.zeros(0, dtype=np.int32)),
    )
    buf, lens, spans, side = pack_updates_v2(v2)
    stream, flags = decode_updates_v2(
        buf, lens, spans, 8, 8,
        client_table=client_table,
        client_hash_table=cht,
    )
    f = np.asarray(flags)
    assert (f & FLAG_ERRORS == 0).all(), f
    valid = np.asarray(stream.valid)
    got = sorted(
        int(np.asarray(stream.client)[0, u])
        for u in range(valid.shape[1])
        if valid[0, u]
    )
    # both blocks present, each client resolved to its DISTINCT index
    assert set(got) == {0, 1}
    dvalid = np.asarray(stream.del_valid)
    ds_clients = {
        int(np.asarray(stream.del_client)[0, r])
        for r in range(dvalid.shape[1])
        if dvalid[0, r]
    }
    assert ds_clients and ds_clients <= {0, 1}

    # without a hash table the lane flags FLAG_BIG_CLIENT
    from ytpu.ops.decode_kernel import FLAG_BIG_CLIENT

    _, flags2 = decode_updates_v2(buf, lens, spans, 8, 8)
    assert np.asarray(flags2)[0] & FLAG_BIG_CLIENT


@pytest.mark.skipif(
    not os.environ.get("YTPU_RUN_SLOW"),
    reason="full-trace V2 decode (minutes); set YTPU_RUN_SLOW=1",
)
def test_b4_full_trace_rides_v2_device_lane():
    """VERDICT r3 #4 'done' criterion, first half: the FULL 259,778-op B4
    editing trace, V2-encoded, decodes on the V2 device lane with ZERO
    host fallbacks (chunked; every lane's flags clean), and a sampled
    chunk integrates to text parity with the host replay."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    from ytpu.models.batch_doc import apply_update_stream, get_string, init_state
    from ytpu.ops.decode_kernel import RawPayloadView, identity_rank

    log, expect, trace = bench.load_full_log()
    v2 = [v1_to_v2(p) for p in log]
    CHUNK = 8192
    total_flagged = 0
    for base in range(0, len(v2), CHUNK):
        part = v2[base : base + CHUNK]
        buf, lens, spans, side = pack_updates_v2(part, pad_to=64)
        stream, flags = decode_updates_v2(buf, lens, spans, 4, 4, sidecar=side)
        f = np.asarray(flags)
        total_flagged += int((f & FLAG_ERRORS != 0).sum())
    assert total_flagged == 0, f"{total_flagged} lanes fell back to host"

    # parity spot-check: integrate the first chunk and compare against a
    # host replay of the same prefix
    n = min(CHUNK, len(log))
    doc = Doc(client_id=99)
    for p in log[:n]:
        doc.apply_update_v1(p)
    buf, lens, spans, side = pack_updates_v2(v2[:n], pad_to=64)
    stream, flags = decode_updates_v2(buf, lens, spans, 4, 4, sidecar=side)
    state = init_state(1, 1 << 14)
    state = apply_update_stream(state, stream, identity_rank(2))
    assert int(np.asarray(state.error).max()) == 0
    got = get_string(state, 0, RawPayloadView(np.asarray(buf)))
    assert got == doc.get_text("text").get_string()


def test_widened_content_kinds_ride_device_lane():
    """VERDICT r3 #4: the V2 columnar decoder's rest WALKER device-decodes
    Any values (depth-1 lists/objects), Binary bufs, map LWW chains (via
    the key table) and Move payloads with ZERO host fallbacks — the V2
    lane's supported set now covers every north-star array/map workload
    shape. (Since round 5, Type/Embed/Format/Json also ride the lane via
    the pack-time V1-form sidecar — see the cold-content tests below;
    only Doc content and weak type tags stay per-lane flagged.)"""
    import jax.numpy as jnp

    from ytpu.models.batch_doc import (
        KeyInterner,
        apply_update_stream,
        get_tree,
        init_state,
    )
    from ytpu.ops.decode_kernel import (
        RawPayloadView,
        identity_rank,
        key_hash_host,
    )

    d = Doc(client_id=3)
    log = []
    d.observe_update_v1(lambda p, o, t: log.append(p))
    arr = d.get_array("a")
    with d.transact() as txn:
        arr.insert_range(txn, 0, [1, "two", 3.5, True, None])
    with d.transact() as txn:
        arr.insert_range(txn, 2, [[1, 2], {"k": 7}])
    with d.transact() as txn:
        arr.insert_range(txn, 0, [b"\x00\xffbinary"])
    with d.transact() as txn:
        arr.remove_range(txn, 2, 2)
    m = d.get_map("a")
    with d.transact() as txn:
        m.insert(txn, "x", 42)
    with d.transact() as txn:
        m.insert(txn, "x", 43)  # LWW replacement (origin-chained)
    with d.transact() as txn:
        arr.move_to(txn, 1, 3)

    v2 = [v1_to_v2(p) for p in log]
    buf, lens, spans, side = pack_updates_v2(v2, pad_to=128)
    keys = KeyInterner()
    kt = (
        jnp.asarray([key_hash_host(b"x")]),
        jnp.asarray([keys.intern("x")]),
    )
    stream, flags = decode_updates_v2(buf, lens, spans, 8, 4, key_table=kt, sidecar=side)
    f = np.asarray(flags)
    assert (f & FLAG_ERRORS == 0).all(), f"host fallbacks: {f}"

    state = init_state(1, 256)
    state = apply_update_stream(state, stream, identity_rank(2))
    assert int(np.asarray(state.error).max()) == 0
    view = RawPayloadView(np.asarray(buf), v2_any=True)
    tree = get_tree(state, 0, view, keys)
    assert tree["seq"] == arr.to_json(), (tree["seq"], arr.to_json())
    assert tree["map"] == {"x": 43}, tree["map"]


def test_nested_any_values_ride_device_lane():
    """Round 5: the rest walker's container STACK device-decodes Any
    values with maps nested to W_DEPTH - 1 = 3 levels and arrays nested
    arbitrarily (r4 flagged anything past depth 1)."""
    from ytpu.ops.decode_kernel import RawPayloadView

    deep_vals = [
        {"deep": [1, 2, 3]},                       # map -> array
        {"a": {"b": 7}, "c": [4, [5, 6]]},         # map -> map / arr -> arr
        [{"x": [1, {"y": 2}]}, 9],                 # arr -> map -> arr -> map
        {"e": [], "f": 2},                         # EMPTY array as pair value
        [{"g": [1, []]}, {}, []],                  # empty arr/map tails
        "plain",
    ]
    d = Doc(client_id=5)
    log = []
    d.observe_update_v1(lambda p, o, t: log.append(p))
    arr = d.get_array("a")
    with d.transact() as txn:
        arr.insert_range(txn, 0, deep_vals)
    with d.transact() as txn:
        arr.insert(txn, 2, {"tail": {"k": [10]}})
    v2 = [v1_to_v2(p) for p in log]
    buf, lens, spans, side = pack_updates_v2(v2, pad_to=256)
    stream, flags = decode_updates_v2(buf, lens, spans, 8, 4, sidecar=side)
    f = np.asarray(flags)
    assert (f & FLAG_ERRORS == 0).all(), f"host fallbacks: {f}"
    view = RawPayloadView(np.asarray(buf), v2_any=True)
    valid = np.asarray(stream.valid)
    refs = np.asarray(stream.content_ref)
    lengths = np.asarray(stream.length)
    got = []
    for s in range(len(v2)):
        for u in range(valid.shape[1]):
            if valid[s, u] and refs[s, u] >= 0:
                got.extend(view.slice_values(refs[s, u], 0, int(lengths[s, u])))
    assert got == deep_vals + [{"tail": {"k": [10]}}], got


def test_too_deep_any_values_fall_back_to_host():
    """Maps nested beyond the walker's W_DEPTH - 1 = 3 levels exceed the
    stacked scope and must flag the lane — never decode wrong."""
    d = Doc(client_id=5)
    log = []
    d.observe_update_v1(lambda p, o, t: log.append(p))
    arr = d.get_array("a")
    with d.transact() as txn:
        arr.insert_range(txn, 0, [{"a": {"b": {"c": {"d": 1}}}}])
    v2 = [v1_to_v2(p) for p in log]
    buf, lens, spans, side = pack_updates_v2(v2, pad_to=128)
    stream, flags = decode_updates_v2(buf, lens, spans, 4, 4, sidecar=side)
    f = np.asarray(flags)
    assert (f & FLAG_UNSUPPORTED != 0).all(), f
    assert not np.asarray(stream.valid).any()  # flagged lanes emit no rows


def test_cold_content_payload_refs_resolve_v1_form():
    """Round 5 (VERDICT r4 #4): Json / Embed / Format / Type content
    structure-decodes on the V2 device lane; each row's payload ref
    points at the pack-time V1-form sidecar span and every V1-shaped
    reader resolves it — validated field-by-field against the host
    decoder."""
    from collections import deque

    from ytpu.core.block import Item
    from ytpu.core.content import ContentJSON
    from ytpu.core.id_set import DeleteSet
    from ytpu.core.ids import ID
    from ytpu.ops.decode_kernel import RawPayloadView
    from ytpu.types import XmlElementPrelim

    d = Doc(client_id=11)
    log = []
    d.observe_update_v1(lambda p, o, t: log.append(p))
    t = d.get_text("t")
    with d.transact() as txn:
        t.insert(txn, 0, "hello world")
    with d.transact() as txn:
        t.format(txn, 0, 5, {"bold": True})
    with d.transact() as txn:
        t.insert_embed(txn, 5, {"img": "x.png"})
    frag = d.get_xml_fragment("x")
    with d.transact() as txn:
        frag.insert(txn, 0, XmlElementPrelim("div", attributes={"id": "a1"}))
    v2 = [v1_to_v2(p) for p in log]
    # hand-crafted legacy ContentJSON carrier (the host lib never emits
    # one; the wire still must decode — block.rs:1786-1835 uniformity)
    ContentJSON  # noqa: B018 — imported for the carrier below
    it = Item(
        ID(99, 0), None, None, None, None, "j", None,
        ContentJSON(["1", '{"a": 2}']),
    )
    up = Update({99: deque([it])}, DeleteSet())
    v2.append(up.encode_v2())

    buf, lens, spans, side = pack_updates_v2(v2, pad_to=256)
    assert side is not None  # cold kinds detected
    import jax.numpy as jnp

    from ytpu.models.batch_doc import KeyInterner
    from ytpu.ops.decode_kernel import key_hash_host

    keys = KeyInterner()
    kt = (
        jnp.asarray([key_hash_host(b"id")]),
        jnp.asarray([keys.intern("id")]),
    )
    stream, flags = decode_updates_v2(
        buf, lens, spans, 8, 4, key_table=kt, sidecar=side
    )
    f = np.asarray(flags)
    assert (f & FLAG_ERRORS == 0).all(), f"host fallbacks: {f}"

    view = RawPayloadView(np.asarray(buf), v2_any=True)
    valid = np.asarray(stream.valid)
    kinds = np.asarray(stream.kind)
    refs = np.asarray(stream.content_ref)
    lengths = np.asarray(stream.length)
    from ytpu.core.content import (
        CONTENT_EMBED as K_EMBED,
        CONTENT_FORMAT as K_FMT,
        CONTENT_JSON as K_JSON,
        CONTENT_TYPE as K_TYPE,
    )

    seen = {"fmt": 0, "embed": 0, "type": 0, "json": 0}
    for s, payload in enumerate(v2):
        hosts = []
        for client, blocks in sorted(Update.decode_v2(payload).blocks.items()):
            hosts.extend(b for b in blocks if getattr(b, "content", None))
        hi = 0
        for u in range(valid.shape[1]):
            if not valid[s, u]:
                continue
            host_content = hosts[hi].content if hi < len(hosts) else None
            hi += 1
            k, ref = int(kinds[s, u]), int(refs[s, u])
            if k == K_FMT:
                key, val = view.format_kv(ref)
                assert (key, val) == (host_content.key, host_content.value)
                seen["fmt"] += 1
            elif k == K_EMBED:
                assert view.embed_value(ref) == host_content.value
                seen["embed"] += 1
            elif k == K_TYPE:
                br = view.type_branch(ref)
                assert br.type_ref == host_content.branch.type_ref
                assert br.type_name == host_content.branch.type_name
                seen["type"] += 1
            elif k == K_JSON:
                assert (
                    view.json_raw(ref, 0, int(lengths[s, u]))
                    == host_content.raw
                )
                seen["json"] += 1
    assert all(v > 0 for v in seen.values()), seen


def test_rich_text_stream_rides_v2_device_lane():
    """Format + embed text streams decode on the V2 lane with zero host
    fallbacks and integrate to the same rich-text runs as the host."""
    from ytpu.models.batch_doc import (
        apply_update_stream,
        get_diff,
        init_state,
    )
    from ytpu.ops.decode_kernel import RawPayloadView, identity_rank

    def ops(doc):
        t = doc.get_text("text")
        with doc.transact() as txn:
            t.insert(txn, 0, "the quick brown fox")
        with doc.transact() as txn:
            t.format(txn, 4, 5, {"b": True})
        with doc.transact() as txn:
            t.insert_embed(txn, 9, {"u": "e.png"})
        with doc.transact() as txn:
            t.format(txn, 4, 5, {"b": None})  # unformat
        with doc.transact() as txn:
            t.remove_range(txn, 0, 4)

    doc, log = capture_v1(ops)
    v2 = [v1_to_v2(p) for p in log]
    buf, lens, spans, side = pack_updates_v2(v2, pad_to=256)
    stream, flags = decode_updates_v2(buf, lens, spans, 8, 4, sidecar=side)
    f = np.asarray(flags)
    assert (f & FLAG_ERRORS == 0).all(), f"host fallbacks: {f}"

    state = init_state(1, 256)
    state = apply_update_stream(state, stream, identity_rank(2))
    assert int(np.asarray(state.error).max()) == 0
    view = RawPayloadView(np.asarray(buf), v2_any=True)
    got = get_diff(state, 0, view)
    want = doc.get_text("text").diff()
    assert [(r.insert, r.attributes) for r in got] == [
        (r.insert, r.attributes) for r in want
    ]
