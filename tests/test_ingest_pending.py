"""Batch-level pending-update semantics (SURVEY §7 hard part: a doc whose
update goes pending must not stall its batch; parity: transaction.rs:675-727
stash-and-retry, update.rs:289-299)."""

from ytpu.core import Doc
from ytpu.models.batch_doc import get_map, get_string, get_tree
from ytpu.models.ingest import BatchIngestor


def txn_payloads(client_id, edits):
    """One payload per transaction from a fresh host doc."""
    doc = Doc(client_id=client_id)
    out = []
    doc.observe_update_v1(lambda p, o, t: out.append(p))
    for fn in edits:
        with doc.transact() as txn:
            fn(doc, txn)
    return doc, out


def test_out_of_order_update_goes_pending_then_applies():
    doc, payloads = txn_payloads(
        7,
        [
            lambda d, t: d.get_text("text").insert(t, 0, "first"),
            lambda d, t: d.get_text("text").insert(t, 5, "-second"),
        ],
    )
    ing = BatchIngestor(n_docs=2, capacity=64)
    # doc slot 0 receives txn2 BEFORE txn1; slot 1 receives them in order
    ing.apply([payloads[1], payloads[0]])
    assert int(ing.state.error.max()) == 0
    assert ing.pending_update(0) is not None  # stashed, not integrated
    assert get_string(ing.state, 0, ing.enc.payloads) == ""
    assert get_string(ing.state, 1, ing.enc.payloads) == "first"

    ing.apply([payloads[0], payloads[1]])  # the missing base arrives
    assert int(ing.state.error.max()) == 0
    assert ing.pending_update(0) is None  # stash drained
    for d in range(2):
        assert get_string(ing.state, d, ing.enc.payloads) == "first-second"


def test_pending_doc_does_not_stall_batch():
    doc_a, pa = txn_payloads(1, [lambda d, t: d.get_text("text").insert(t, 0, "a0"),
                                 lambda d, t: d.get_text("text").insert(t, 2, "a1")])
    doc_b, pb = txn_payloads(2, [lambda d, t: d.get_text("text").insert(t, 0, "b0")])
    ing = BatchIngestor(n_docs=2, capacity=64)
    # slot 0 gets a dependent update with no base (pending); slot 1 is normal
    ing.apply([pa[1], pb[0]])
    assert int(ing.state.error.max()) == 0
    assert get_string(ing.state, 1, ing.enc.payloads) == "b0"  # not stalled
    assert ing.pending_update(0) is not None


def test_pending_delete_set_defers_and_applies():
    doc, payloads = txn_payloads(
        3,
        [
            lambda d, t: d.get_text("text").insert(t, 0, "abcdef"),
            lambda d, t: d.get_text("text").remove_range(t, 1, 3),
        ],
    )
    ing = BatchIngestor(n_docs=1, capacity=64)
    ing.apply([payloads[1]])  # delete arrives before the content
    assert ing.pending_ds(0) is not None
    assert get_string(ing.state, 0, ing.enc.payloads) == ""
    ing.apply([payloads[0]])
    assert int(ing.state.error.max()) == 0
    assert ing.pending_ds(0) is None
    assert get_string(ing.state, 0, ing.enc.payloads) == doc.get_text("text").get_string() == "aef"


def test_interleaved_multi_client_catchup():
    """Cross-client deps: client B quotes A's content; B's update arrives
    first, then A's — both integrate once the stash drains."""
    a = Doc(client_id=10)
    with a.transact() as txn:
        a.get_text("text").insert(txn, 0, "base")
    ua = a.encode_state_as_update_v1()
    b = Doc(client_id=20)
    b.apply_update_v1(ua)
    captured = []
    b.observe_update_v1(lambda p, o, t: captured.append(p))
    with b.transact() as txn:
        b.get_text("text").insert(txn, 4, "-tail")  # origin in A's range
    ub = captured[0]

    ing = BatchIngestor(n_docs=1, capacity=64)
    ing.apply([ub])  # depends on A's blocks → pending
    assert get_string(ing.state, 0, ing.enc.payloads) == ""
    ing.apply([ua])
    assert int(ing.state.error.max()) == 0
    assert get_string(ing.state, 0, ing.enc.payloads) == "base-tail"
    assert ing.pending_update(0) is None


def test_map_and_tree_through_ingestor():
    doc, payloads = txn_payloads(
        5,
        [
            lambda d, t: d.get_map("text").insert(t, "k", 1),
            lambda d, t: d.get_map("text").insert(t, "k", 2),
        ],
    )
    ing = BatchIngestor(n_docs=1, capacity=64)
    ing.apply([payloads[1]])  # overwrite before base -> pending
    ing.apply([payloads[0]])
    assert int(ing.state.error.max()) == 0
    assert get_map(ing.state, 0, ing.enc.payloads, ing.enc.keys) == {"k": 2}


def test_redelivery_does_not_grow_stash():
    """Exact re-sends of a stuck update dedupe instead of accumulating."""
    doc, payloads = txn_payloads(
        9,
        [
            lambda d, t: d.get_text("text").insert(t, 0, "base"),
            lambda d, t: d.get_text("text").insert(t, 4, "-dep"),
        ],
    )
    ing = BatchIngestor(n_docs=1, capacity=64)
    for _ in range(4):  # same dependent payload redelivered 4x
        ing.apply([payloads[1]])
    stash = ing.pending_update(0)
    assert stash is not None
    assert sum(len(q) for q in stash.blocks.values()) == 1  # deduped
    n_payload_entries = len(ing.enc.payloads.items)

    ing.apply([payloads[0]])
    assert get_string(ing.state, 0, ing.enc.payloads) == "base-dep"
    assert ing.pending_update(0) is None
    # already-applied redelivery is dropped host-side, not re-stashed
    ing.apply([payloads[1]])
    assert ing.pending_update(0) is None
    assert get_string(ing.state, 0, ing.enc.payloads) == "base-dep"
