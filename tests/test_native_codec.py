"""Native (C++) lib0 decoder parity vs the Python decoder."""

import random
import string

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.native import available, decode_update_columns

pytestmark = pytest.mark.skipif(
    not available(), reason="native codec unavailable (no g++?)"
)


def flatten_python(update: Update):
    """Python-decoded update → comparable row list (client-desc order)."""
    rows = []
    for client in sorted(update.blocks.keys(), reverse=True):
        for b in update.blocks[client]:
            rows.append((b.id.client, b.id.clock, b.len))
    return sorted(rows)


def native_rows(cols):
    return sorted(
        zip(cols.client.tolist(), cols.clock.tolist(), cols.length.tolist())
    )


def test_native_matches_python_on_random_docs():
    rng = random.Random(5)
    doc = Doc(client_id=77)
    t = doc.get_text("t")
    m = doc.get_map("m")
    a = doc.get_array("a")
    with doc.transact() as txn:
        for _ in range(30):
            word = "".join(rng.choice(string.ascii_lowercase) for _ in range(5))
            t.insert(txn, rng.randint(0, len(t)), word + "é😀")
            m.insert(txn, rng.choice("xyz"), [1, {"k": "v"}, None])
            a.push_back(txn, rng.random())
    with doc.transact() as txn:
        t.remove_range(txn, 3, 10)
    payload = doc.encode_state_as_update_v1()
    cols = decode_update_columns(payload)
    assert cols is not None and not cols.error
    u = Update.decode_v1(payload)
    assert native_rows(cols) == flatten_python(u)
    # delete set parity
    py_dels = sorted(
        (c, s, e) for c, rs in u.delete_set.clients.items() for s, e in rs
    )
    nat_dels = sorted(
        zip(cols.del_client.tolist(), cols.del_start.tolist(), cols.del_end.tolist())
    )
    assert nat_dels == py_dels


def test_native_string_utf16_lengths():
    doc = Doc(client_id=1)
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "a😀b")  # 4 utf-16 units
    payload = doc.encode_state_as_update_v1()
    cols = decode_update_columns(payload)
    assert cols.length.tolist() == [4]


def test_native_parent_and_sub_spans():
    doc = Doc(client_id=1)
    m = doc.get_map("mymap")
    with doc.transact() as txn:
        m.insert(txn, "thekey", "val")
    payload = doc.encode_state_as_update_v1()
    cols = decode_update_columns(payload)
    assert cols.parent_kind.tolist() == [1]
    assert cols.parent_name(0) == "mymap"
    assert cols.parent_sub(0) == "thekey"


def test_native_handles_yjs_capture():
    from tests.test_yjs_compat import TEXT_UPDATE, TEXT_CLIENT

    cols = decode_update_columns(TEXT_UPDATE)
    assert not cols.error
    assert cols.n_blocks == 5
    assert all(c == TEXT_CLIENT for c in cols.client.tolist())
    assert cols.clock.tolist() == [0, 3, 5, 6, 7]
    assert cols.length.tolist() == [3, 2, 1, 1, 2]
