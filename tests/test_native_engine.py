"""C++ scalar YATA engine (`ytpu/native/engine.cpp`) — the native-speed
baseline. Oracle: the host `ytpu.core.Doc` replaying the same streams.

Reference semantics covered: YATA conflict scan with client-id tie-break
(yrs/src/block.rs:537-602), block splits on mid-block origins and delete
boundaries (block_store.rs:402-417), apply_delete (transaction.rs:472-575),
partial-redelivery offsets (block.rs:482 `offset` param), UTF-16 content
lengths (block.rs:1386-1502).
"""

import random

import pytest

from ytpu.core import Doc
from ytpu.native import (
    NativeEngine,
    NativeUnsupported,
    engine_available,
    native_replay_v1,
)

needs_native = pytest.mark.skipif(
    not engine_available(), reason="native engine unavailable"
)


def _edit_log(ops, client_id=1):
    doc = Doc(client_id=client_id)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text("text")
    for tag, pos, arg in ops:
        with doc.transact() as txn:
            if tag == "i":
                txt.insert(txn, pos, arg)
            else:
                txt.remove_range(txn, pos, arg)
    return log, txt.get_string()


@needs_native
def test_sequential_inserts_deletes():
    ops = [
        ("i", 0, "hello world"),
        ("i", 5, ","),
        ("d", 2, 4),
        ("i", 0, ">> "),
        ("d", 0, 1),
        ("i", 8, "XYZ"),
    ]
    log, expect = _edit_log(ops)
    assert native_replay_v1(log) == expect


@needs_native
def test_utf16_surrogates_and_multibyte():
    ops = [
        ("i", 0, "aπc🙂e"),
        ("i", 2, "🙈🙉"),
        ("d", 1, 3),
        ("i", 0, "ß"),
    ]
    log, expect = _edit_log(ops)
    assert native_replay_v1(log) == expect


@needs_native
def test_random_single_client_fuzz():
    rng = random.Random(42)
    ops = []
    length = 0
    for _ in range(400):
        if length > 5 and rng.random() < 0.35:
            pos = rng.randint(0, length - 2)
            n = rng.randint(1, min(5, length - pos))
            ops.append(("d", pos, n))
            length -= n
        else:
            word = "".join(
                rng.choice("abcdefgπ🙂") for _ in range(rng.randint(1, 6))
            )
            ops.append(("i", rng.randint(0, length), word))
            length += len(word)
    log, expect = _edit_log(ops)
    assert native_replay_v1(log) == expect


@needs_native
def test_concurrent_two_client_convergence():
    """Concurrent edits exchanged both ways: the YATA conflict scan must
    order same-position inserts identically to the host engine."""
    rng = random.Random(7)
    a, b = Doc(client_id=1), Doc(client_id=2)
    log_a, log_b = [], []
    a.observe_update_v1(lambda p, o, t: log_a.append(p))
    b.observe_update_v1(lambda p, o, t: log_b.append(p))
    ta, tb = a.get_text("text"), b.get_text("text")

    interleaved = []  # causal application order for the engine
    for round_ in range(30):
        for doc, txt, log, mark in ((a, ta, log_a, "A"), (b, tb, log_b, "B")):
            n = len(txt.get_string())
            with doc.transact() as txn:
                if n > 4 and rng.random() < 0.3:
                    pos = rng.randint(0, n - 2)
                    txt.remove_range(txn, pos, rng.randint(1, 2))
                else:
                    txt.insert(txn, rng.randint(0, n), f"{mark}{round_}")
            interleaved.append(log[-1])
        # exchange after each round so dependencies stay satisfied (use the
        # captured payloads — observers also fire on remote applies)
        pa, pb = interleaved[-2], interleaved[-1]
        b.apply_update_v1(pa)
        a.apply_update_v1(pb)
    assert ta.get_string() == tb.get_string()

    eng = NativeEngine()
    for p in interleaved:
        eng.apply_update_v1(p)
    assert eng.text() == ta.get_string()
    eng.close()


@needs_native
def test_duplicate_and_partial_redelivery():
    ops = [("i", 0, "abcdef"), ("i", 3, "XY"), ("d", 1, 2)]
    log, expect = _edit_log(ops)
    eng = NativeEngine()
    for p in log:
        eng.apply_update_v1(p)
        eng.apply_update_v1(p)  # exact duplicate: idempotent
    assert eng.text() == expect
    eng.close()


@needs_native
def test_unsupported_stream_raises():
    # moves are the remaining out-of-scope content kind (map keys and
    # nested parents are in scope since round 5)
    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    arr = doc.get_array("a")
    with doc.transact() as txn:
        arr.insert_range(txn, 0, [1, 2, 3])
    with doc.transact() as txn:
        arr.move_to(txn, 0, 2)
    with pytest.raises(NativeUnsupported):
        native_replay_v1(log)


@needs_native
def test_map_and_nested_xml_parity():
    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    m = doc.get_map("m")
    from ytpu.types import XmlElementPrelim

    frag = doc.get_xml_fragment("x")
    with doc.transact() as txn:
        m.insert(txn, "k", "v")
        m.insert(txn, "n", [1, {"a": True}])
        frag.insert(txn, 0, XmlElementPrelim("div", attributes={"id": "d1"}))
    with doc.transact() as txn:
        m.insert(txn, "k", "v2")  # overwrite: last write wins
        m.remove(txn, "n")
    eng = NativeEngine()
    for p in log:
        eng.apply_update_v1(p)
    assert eng.root_json("m", "map") == m.to_json()
    assert eng.root_json("x", "seq") == [
        {"name": "div", "attrs": {"id": "d1"}, "children": []}
    ]
    eng.close()


@needs_native
def test_concurrent_array_parity():
    import importlib
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "benches"))
    dev = importlib.import_module("device")
    log, expect = dev.stream_workload_array(n_clients=24, ops_per_client=2, seed=3)
    eng = NativeEngine()
    for p in log:
        eng.apply_update_v1(p)
    assert eng.root_json("a", "seq") == expect
    eng.close()


@needs_native
def test_b4_trace_prefix_parity():
    import bench

    try:
        ops = bench.load_b4_ops(3000)
    except FileNotFoundError:
        ops = bench.synthetic_ops(3000)
    log, expect = bench.build_updates(ops)
    assert native_replay_v1(log) == expect
