"""Reference regression scenarios replayed against ytpu.

The byte-capture cases read their wire fixtures AT RUNTIME from the
mounted reference sources (yrs/src/doc.rs test bodies) — the captures are
real-world update streams from downstream bug reports (ypy#32,
y-crdt#174, yrb#45), i.e. exactly the cross-implementation corpus the
test strategy calls for (SURVEY §4 port priority c). Behavior-only cases
(y-crdt#186 move iteration, empty-range insert, yjs#101 format deltas)
are written directly against our API.
"""

import os
import re

import pytest

from ytpu.core import Doc
from ytpu.types.events import Change

_DOC_RS = "/root/reference/yrs/src/doc.rs"

requires_reference = pytest.mark.skipif(
    not os.path.exists(_DOC_RS), reason="reference sources not mounted"
)


def _byte_vecs(fn_name: str):
    """Extract the `vec![..]` byte fixtures of one reference test fn."""
    src = open(_DOC_RS).read()
    i = src.index(f"fn {fn_name}")
    j = src.find("#[test]", i)
    body = src[i : j if j > 0 else len(src)]
    out = []
    for m in re.finditer(r"(?:vec!|&)\[([\d,\s]+)\]", body):
        nums = [int(x) for x in m.group(1).replace("\n", "").split(",") if x.strip()]
        if len(nums) > 4:  # skip tiny index literals
            out.append(bytes(nums))
    return out


@requires_reference
def test_ypy_issue_32_pending_skip_updates():
    """Out-of-order updates with skips must stash and retry without
    corrupting existing content, then drain when the gap fills (ypy#32).
    Staged exactly like the reference: 4 captures -> "a", full sync to a
    fresh peer, 5th capture fills the gap -> "ab", sync again."""
    vecs = _byte_vecs("ypy_issue_32")
    assert len(vecs) == 5
    d1 = Doc(client_id=1971027812)
    src = d1.get_text("source")
    with d1.transact() as txn:
        src.insert(txn, 0, "a")
    for payload in vecs[:4]:
        d1.apply_update_v1(payload)
    assert src.get_string() == "a"

    d2 = Doc(client_id=2)
    d2.apply_update_v1(d1.encode_state_as_update_v1(d2.state_vector()))
    assert d2.get_text("source").get_string() == "a"

    d1.apply_update_v1(vecs[4])
    assert src.get_string() == "ab"
    d3 = Doc(client_id=3)
    d3.apply_update_v1(d1.encode_state_as_update_v1(d3.state_vector()))
    assert d3.get_text("source").get_string() == "ab"


@requires_reference
def test_ycrdt_issue_174_v2_capture():
    """A captured v2 update with every root flavor decodes and applies to
    the documented tree (y-crdt#174)."""
    (payload,) = _byte_vecs("ycrdt_issue_174")
    doc = Doc(client_id=9)
    doc.apply_update_v2(payload)
    root = doc.get_map("root")
    assert root.to_json() == {
        "string": "world",
        "a_list": [{"b": "a", "a": 1}],
        "i32_map": {"1": 2},
        "a_map": {"1": {"a": 2, "b": "b"}},
        "string_list": ["a"],
        "i32": 2,
        "string_map": {"1": "b"},
        "i32_list": [1],
    }


@requires_reference
def test_yrb_issue_45_update_storm():
    """~100 captured v1 diffs (heavy out-of-order delivery) apply without
    error and re-encode to a convergent replica (yrb#45)."""
    diffs = _byte_vecs("yrb_issue_45")
    assert len(diffs) > 30
    doc = Doc(client_id=3)
    for payload in diffs:
        doc.apply_update_v1(payload)
    replica = Doc(client_id=4)
    replica.apply_update_v1(doc.encode_state_as_update_v1())
    assert (
        replica.get_text("text").get_string()
        == doc.get_text("text").get_string()
    )


def test_move_last_elem_iter_issue_186():
    doc = Doc(client_id=1)
    arr = doc.get_array("array")
    with doc.transact() as txn:
        arr.insert_range(txn, 0, [1, 2, 3])
    with doc.transact() as txn:
        arr.move_to(txn, 2, 0)
    assert arr.to_json() == [3, 1, 2]


def test_insert_empty_range():
    doc = Doc(client_id=1)
    arr = doc.get_array("array")
    with doc.transact() as txn:
        arr.insert(txn, 0, 1)
        arr.insert_range(txn, 1, [])
        arr.push_back(txn, 2)
    assert arr.to_json() == [1, 2]
    d2 = Doc(client_id=2)
    d2.apply_update_v1(doc.encode_state_as_update_v1())
    assert d2.get_array("array").to_json() == [1, 2]


def test_issue_101_format_event_delta():
    """Formatting the middle of a text yields [retain, retain+attrs]."""
    doc = Doc(client_id=1)
    txt = doc.get_text("text")
    with doc.transact() as txn:
        txt.insert(txn, 0, "abcd")
    deltas = []
    txt.observe(lambda txn, e: deltas.append(e.delta()))
    with doc.transact() as txn:
        txt.format(txn, 1, 2, {"bold": True})
    assert deltas == [[Change.retain(1), Change.retain(2, {"bold": True})]]
