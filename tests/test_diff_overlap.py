"""Pipelined encode/diff (ISSUE-10): staged selection → D2H → batched
native finisher.

Covers: pipelined-vs-serial byte parity (including Python-fallback rows
mixed into a sub-batch — a wire-ref Embed/Format doc the native core
punts on), the zero-extra-device-syncs contract (counted host
materializations + exact D2H byte accounting), the stall/overlap gauge
contract, the pow2 recompile bound on the packed widths, the rows-based
finisher threading heuristic, and the `diff.d2h_fail`/`finisher.raise`
degradation classes.

Suite-cost hygiene: ONE compiled shape family for the whole file — the
(n_docs=4, capacity=256) ingest family test_device_server.py already
compiles — built once at module scope; the DiffPipeline's own pack
program compiles one (sub=2, R) instance reused by every test.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ytpu.native import available as native_available
from ytpu.utils import metrics
from ytpu.utils.faults import faults

needs_native = pytest.mark.skipif(
    not native_available(), reason="native library unavailable"
)

N_DOCS, CAPACITY = 4, 256  # the suite-wide device-server shape family
SUB, DEPTH = 2, 2

_FAM: dict = {}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _family() -> dict:
    """Docs 0/1/3 are plain/emoji/deleted text (native-scope rows); doc 2
    carries wire-ref Embed + Format rows through the ingest fast lane —
    outside the native finisher's scope, so every batch call peels it
    per doc in Python (the mixed-sub-batch fallback case)."""
    if _FAM:
        return _FAM
    from ytpu.core import Doc
    from ytpu.models import batch_doc as bd
    from ytpu.models.ingest import BatchIngestor

    docs, logs = [], []
    for i in range(N_DOCS):
        d = Doc(client_id=i + 1)
        log = []
        d.observe_update_v1(lambda p, o, t, log=log: log.append(p))
        t = d.get_text("text")
        with d.transact() as txn:
            t.insert(txn, 0, f"doc{i} body")
        if i == 2:
            with d.transact() as txn:
                t.insert_embed(txn, 2, {"img": "x.png"})
            with d.transact() as txn:
                t.insert_with_attributes(txn, 0, "b", {"bold": True})
        else:
            with d.transact() as txn:
                t.insert(txn, 3, "✓🙂" if i else "tail")
        if i == 1:
            with d.transact() as txn:
                t.remove_range(txn, 1, 3)
        docs.append(d)
        logs.append(log)
    ing = BatchIngestor(N_DOCS, CAPACITY)
    for step in range(max(len(lg) for lg in logs)):
        ing.apply_bytes([lg[step] if step < len(lg) else None for lg in logs])
    assert int(np.asarray(ing.state.error).max()) == 0
    assert ing.fast_docs > 0  # doc 2's rows really are wire refs
    n_clients = max(8, len(ing.enc.interner))
    remote = np.zeros((N_DOCS, n_clients), dtype=np.int32)
    ship, offsets, _sv, deleted = bd.encode_diff_batch(
        ing.state, jnp.asarray(remote), n_clients
    )
    serial = bd.finish_encode_diff_batch(
        ing.state,
        list(range(N_DOCS)),
        ship,
        offsets,
        deleted,
        ing.enc,
        payloads=ing.payloads,
    )
    _FAM.update(
        ing=ing,
        docs=docs,
        ship=ship,
        offsets=offsets,
        deleted=deleted,
        serial=serial,
        fallback_statuses=list(bd.LAST_FINISH_STATUSES),
    )
    return _FAM


def _run_pipe(sel, sub_batch=SUB, depth=DEPTH):
    from ytpu.models.batch_doc import DiffPipeline

    fam = _family()
    pipe = DiffPipeline(sub_batch=sub_batch, depth=depth)
    out = pipe.run(
        fam["ing"].state,
        sel,
        fam["ship"],
        fam["offsets"],
        fam["deleted"],
        fam["ing"].enc,
        payloads=fam["ing"].payloads,
    )
    return pipe, out


@needs_native
def test_pipelined_matches_serial_with_fallback_rows_in_sub_batch():
    """Byte parity over the full selection, with doc 2's wire-ref
    Embed/Format rows forcing a per-doc Python peel INSIDE the second
    sub-batch while its neighbor stays native."""
    fam = _family()
    # the family's serial call really exercised the mixed case
    assert fam["fallback_statuses"] == [0, 0, 1, 0]
    pipe, out = _run_pipe(list(range(N_DOCS)))
    assert out == fam["serial"]
    assert pipe.stats.n_sub == 2 and pipe.stats.sub == SUB
    assert pipe.stats.fallback_docs == 1
    assert pipe.stats.demotions == 0
    # every payload replays into a correct replica
    from ytpu.core import Doc

    for i, payload in enumerate(out):
        r = Doc(client_id=99)
        r.apply_update_v1(payload)
        assert r.get_text("text").diff() == fam["docs"][i].get_text(
            "text"
        ).diff(), f"doc {i}"


@needs_native
def test_zero_extra_device_syncs_and_exact_d2h_accounting():
    """The pipeline performs exactly n_sub + 1 blocking host
    materializations (ONE counts pull + one drain per sub-batch) and the
    drained bytes are exactly n_sub * sub * 15 * R * 4 — any per-doc
    readout would break both counts.  Selection avoids the fallback doc
    (its Python peel legitimately pulls the full arrays)."""
    pipe, out = _run_pipe([0, 1, 3, 0])  # repeats are legal; no doc 2
    st = pipe.stats
    assert st.fallback_docs == 0
    assert st.n_sub == 2
    assert st.syncs == st.n_sub + 1, st
    assert st.d2h_bytes == st.n_sub * st.sub * 15 * st.R * 4, st
    fam = _family()
    assert out == [fam["serial"][0], fam["serial"][1], fam["serial"][3],
                   fam["serial"][0]]


@needs_native
def test_stall_overlap_gauge_contract():
    """With phases enabled, a multi-sub-batch run lands the documented
    encode gauges: select/finish/d2h_bytes plus the engine's
    stage/drain/stall/overlap_ratio/inflight_depth."""
    from ytpu.utils.phases import phases

    was_enabled = phases.enabled
    phases.reset()
    phases.enable()
    try:
        pipe, _ = _run_pipe(list(range(N_DOCS)))
        snap = phases.snapshot()
    finally:
        if not was_enabled:
            phases.disable()
    for key in (
        "encode.select",
        "encode.finish",
        "encode.d2h_bytes",
        "encode.stage",
        "encode.drain",
        "encode.stall",
        "encode.overlap_ratio",
        "encode.inflight_depth",
    ):
        assert key in snap, (key, sorted(snap))
    assert 0.0 <= snap["encode.overlap_ratio"]["value"] <= 1.0
    assert snap["encode.d2h_bytes"]["value"] == pipe.stats.d2h_bytes > 0
    assert snap["encode.d2h"]["d2h_bytes"] == pipe.stats.d2h_bytes
    assert snap["encode.select"]["calls"] == pipe.stats.n_sub
    assert 0.0 <= pipe.stats.overlap_ratio <= 1.0
    # the single-sub-batch (serving) path emits the stage gauges too,
    # just without an overlap ratio to report
    phases.reset()
    phases.enable()
    try:
        _run_pipe([1], sub_batch=64)
        snap1 = phases.snapshot()
    finally:
        if not was_enabled:
            phases.disable()
    assert "encode.select" in snap1 and "encode.finish" in snap1


@needs_native
def test_packed_width_recompile_bound():
    """Distinct selection lengths inside one pow2 bucket must share ONE
    compiled counts/pack family — the recompile-bounding contract of the
    pow2-rounded doc width and finisher row width (ISSUE-10 small fix:
    `growths` stays bounded)."""
    from ytpu.models import batch_doc as bd

    fam = _family()

    def caches():
        return (
            bd.compact_finisher_rows._cache_size(),
            bd._finish_counts._cache_size(),
        )

    # warm the (8, R) full-batch family once
    bd.finish_encode_diff_batch(
        fam["ing"].state, [0, 1, 3], fam["ship"], fam["offsets"],
        fam["deleted"], fam["ing"].enc, payloads=fam["ing"].payloads,
    )
    before = caches()
    for sel in ([0, 1, 3], [3, 1, 0, 2], [1, 0, 3, 2, 0], [0] * 7):
        got = bd.finish_encode_diff_batch(
            fam["ing"].state, sel, fam["ship"], fam["offsets"],
            fam["deleted"], fam["ing"].enc, payloads=fam["ing"].payloads,
        )
        assert got == [fam["serial"][d] for d in sel]
    after = caches()
    assert after == before, (
        f"selection-length retraces crept in: {before} -> {after}"
    )


def test_sub_batch_plan_is_pow2_and_reuses_one_slot():
    from ytpu.models.batch_doc import plan_diff_pipeline

    for n, sub_batch in ((12, 4), (10240, 512), (3, 512), (1, 512)):
        plan = plan_diff_pipeline(n, sub_batch=sub_batch)
        assert plan.sub & (plan.sub - 1) == 0, plan
        assert plan.n_sub == -(-n // plan.sub)
        assert plan.idx_buffers == 1
        assert plan.buffer_reuses == max(0, plan.n_sub - 1)
        assert plan.donate_idx
    empty = plan_diff_pipeline(0)
    assert empty.n_sub == 0 and empty.buffer_reuses == 0


def test_finisher_thread_heuristic_keys_on_rows_not_docs():
    """ISSUE-10 small fix: the native finisher threading decision is a
    threshold on TOTAL selected rows.  A few huge docs reach the pool
    (the old `len(docs) >= 128` rule left them single-threaded); many
    near-empty docs no longer pay pool spawn overhead."""
    from ytpu.models.batch_doc import (
        FINISHER_MT_MIN_ROWS,
        _finisher_threads,
    )

    # one huge doc: rows alone cross the threshold → pool (0)
    assert _finisher_threads(FINISHER_MT_MIN_ROWS) == 0
    assert _finisher_threads(FINISHER_MT_MIN_ROWS * 10) == 0
    # 200 docs × 2 rows (the old rule's pool case) stays single-threaded
    assert _finisher_threads(400) == 1
    assert _finisher_threads(0) == 1
    assert _finisher_threads(FINISHER_MT_MIN_ROWS - 1) == 1


@needs_native
@pytest.mark.parametrize("site", ["diff.d2h_fail", "finisher.raise"])
def test_fault_degrades_sub_batch_to_serial_path_with_parity(site):
    """A failing sub-batch demotes to the serial per-doc finisher
    (counted via `encode.demotions`) instead of dropping the diff."""
    fam = _family()
    spec = faults.arm(site)
    base = metrics.counter("encode.demotions").value
    pipe, out = _run_pipe(list(range(N_DOCS)))
    assert spec.fired == 1
    assert out == fam["serial"], f"{site}: degraded sub-batch lost parity"
    assert pipe.stats.demotions >= 1
    assert metrics.counter("encode.demotions").value - base >= 1


@needs_native
def test_empty_and_out_of_range_selections():
    from ytpu.models.batch_doc import DiffPipeline

    fam = _family()
    pipe = DiffPipeline(sub_batch=SUB, depth=DEPTH)
    assert (
        pipe.run(
            fam["ing"].state, [], fam["ship"], fam["offsets"],
            fam["deleted"], fam["ing"].enc, payloads=fam["ing"].payloads,
        )
        == []
    )
    with pytest.raises(IndexError, match="doc selection out of range"):
        pipe.run(
            fam["ing"].state, [N_DOCS], fam["ship"], fam["offsets"],
            fam["deleted"], fam["ing"].enc, payloads=fam["ing"].payloads,
        )
