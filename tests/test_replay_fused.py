"""Full-trace fused replay (ytpu/models/replay.py): chunked device decode +
fused integrate + packed compaction + capacity growth, vs the host oracle.

Runs in Pallas interpret mode on the CPU mesh; small capacities force the
compaction/growth machinery to fire many times mid-replay.
"""

import random

import numpy as np
import pytest

from ytpu.core import Doc
from ytpu.native import available as native_available

needs_native = pytest.mark.skipif(
    not native_available(), reason="native codec unavailable (plan pre-scan)"
)


from _fused_interpret import run_or_skip as _interpret_or_skip


def run_or_skip(rep, log):
    """Drive a FusedReplay, SKIPPING when this container's jax cannot
    interpret Pallas TPU kernels (NotImplementedError from the
    interpreter — environmental, present at seed; see
    docs/known_backend_issues.md §3). Real-hardware parity is covered by
    benches/flagship_fused_chunked.py and the mosaic ladder. The skip is
    memoized across files (tests/_fused_interpret.py) so only the first
    fused interpret test in the session pays the kernel trace."""
    return _interpret_or_skip(lambda: rep.run(log))


def _edit_log(ops, client_id=1):
    doc = Doc(client_id=client_id)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text("text")
    for tag, pos, arg in ops:
        with doc.transact() as txn:
            if tag == "i":
                txt.insert(txn, pos, arg)
            else:
                txt.remove_range(txn, pos, arg)
    return log, txt.get_string()


def _fuzz_ops(n, seed, alphabet="abcdefg π🙂"):
    rng = random.Random(seed)
    ops = []
    length = 0
    for _ in range(n):
        if length > 5 and rng.random() < 0.3:
            pos = rng.randint(0, length - 2)
            k = rng.randint(1, min(4, length - pos))
            ops.append(("d", pos, k))
            length -= k
        else:
            word = "".join(rng.choice(alphabet) for _ in range(rng.randint(1, 4)))
            ops.append(("i", rng.randint(0, length), word))
            length += len(word)
    return ops


@needs_native
def test_replay_with_compaction_and_growth():
    from ytpu.models.replay import FusedReplay, plan_replay

    log, expect = _edit_log(_fuzz_ops(400, seed=3))
    plan = plan_replay(log)
    rep = FusedReplay(
        n_docs=8,
        plan=plan,
        capacity=128,  # tiny: forces many compactions + growth
        max_capacity=4096,
        d_block=8,
        chunk=64,
        interpret=True,
    )
    stats = run_or_skip(rep, log)
    assert stats.compactions >= 1, "compaction never fired"
    assert rep.get_string(0) == expect
    assert rep.get_string(7) == expect


@needs_native
def test_sequential_typing_squashes_to_few_blocks():
    """Unit-addressed refs make cross-update typing runs mergeable: a pure
    append stream must collapse to a handful of blocks, not one per
    keystroke (try_squash parity, block.rs:775-799)."""
    from ytpu.models.replay import FusedReplay, plan_replay

    ops = [("i", i, "abcdefgh"[i % 8]) for i in range(300)]
    log, expect = _edit_log(ops)
    plan = plan_replay(log)
    rep = FusedReplay(
        n_docs=8,
        plan=plan,
        capacity=128,
        max_capacity=1024,
        d_block=8,
        chunk=64,
        interpret=True,
    )
    stats = run_or_skip(rep, log)
    assert rep.get_string(0) == expect
    # all 300 keystrokes (one block each on arrival) must collapse into a
    # handful of runs once a commit-style compaction has seen them
    assert rep.compact() <= 4, stats
    assert rep.get_string(0) == expect


@needs_native
def test_replay_matches_b4_prefix():
    import bench
    from ytpu.models.replay import FusedReplay, plan_replay

    try:
        ops = bench.load_b4_ops(800)
    except FileNotFoundError:
        ops = bench.synthetic_ops(800)
    log, expect = bench.build_updates(ops)
    plan = plan_replay(log)
    rep = FusedReplay(
        n_docs=8,
        plan=plan,
        capacity=256,
        max_capacity=8192,
        d_block=8,
        chunk=128,
        interpret=True,
    )
    stats = run_or_skip(rep, log)
    assert rep.get_string(0) == expect
    assert rep.get_string(7) == expect
    assert stats.chunks == (len(log) + 127) // 128


@needs_native
def test_unit_arena_view_surrogate_halves():
    from ytpu.models.replay import UnitArenaView

    # arena: "a🙂b" -> units: a=1, 🙂=2, b=1 (4 units total)
    arena = "a🙂b".encode("utf-8")
    unit_byte = np.array([0, 1, 1, 5, len(arena)], dtype=np.int64)
    v = UnitArenaView(unit_byte, arena)
    assert v.slice_text(0, 0, 4) == "a🙂b"
    assert v.slice_text(0, 0, 2) == "a�"  # cuts the pair
    assert v.slice_text(0, 2, 2) == "�b"  # starts at the second half
    assert v.slice_text(1, 0, 2) == "🙂"
    assert v.slice_text(0, 1, 2) == "🙂"


def test_xla_lane_replay_parity():
    """The un-fused XLA replay lane (bench fallback when Mosaic cannot
    compile the Pallas kernel on real hardware) must render the same text
    as the host oracle through compaction and growth."""
    import bench as _bench
    from ytpu.models.replay import FusedReplay, plan_replay

    ops = _bench.synthetic_ops(300, seed=13)
    log, expect = _bench.build_updates(ops)
    rep = FusedReplay(
        n_docs=8,
        plan=plan_replay(log),
        capacity=512,
        max_capacity=4096,
        d_block=4,
        chunk=64,
        lane="xla",
    )
    rep.run(log)
    assert rep.get_string(0) == expect
    assert rep.get_string(7) == expect
