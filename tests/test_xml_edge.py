"""XML tree edge cases (model: reference types/xml.rs test corpus).

The mixin design (`ytpu/types/xml.py`) is denser than the reference's
1,897-line xml.rs; this module proves the edge-case surface the density
hides: navigation across tombstones, attribute LWW under concurrency,
TreeWalker order over deep nesting, serialization parity across both
wire formats, and concurrent sibling insertion convergence.
"""

import pytest

from ytpu.core import Doc, Update
from ytpu.types import XmlElementPrelim, XmlTextPrelim


def two_way_sync(a: Doc, b: Doc) -> None:
    b.apply_update_v1(a.encode_state_as_update_v1(b.state_vector()))
    a.apply_update_v1(b.encode_state_as_update_v1(a.state_vector()))


def build_tree(d: Doc):
    frag = d.get_xml_fragment("x")
    with d.transact() as txn:
        div = frag.insert(txn, 0, XmlElementPrelim("div", attributes={"id": "root"}))
    with d.transact() as txn:
        div.insert(txn, 0, XmlElementPrelim("span"))
        div.insert(txn, 1, XmlTextPrelim("mid"))
        div.insert(txn, 2, XmlElementPrelim("b"))
    return frag, div


def test_navigation_across_tombstones():
    d = Doc(client_id=1)
    frag, div = build_tree(d)
    kids = list(div.children())
    assert [getattr(k, "tag", "#text") for k in kids] == ["span", "#text", "b"]
    # delete the middle text node; siblings must skip the tombstone
    with d.transact() as txn:
        div.remove_range(txn, 1, 1)
    span, b = list(div.children())
    assert span.next_sibling().tag == "b"
    assert b.prev_sibling().tag == "span"
    assert b.next_sibling() is None
    assert span.prev_sibling() is None
    assert span.parent().tag == "div"


def test_first_child_and_treewalker_order():
    d = Doc(client_id=1)
    frag, div = build_tree(d)
    with d.transact() as txn:
        span = div.first_child()
        span.insert(txn, 0, XmlElementPrelim("i"))
    walk = [
        getattr(n, "tag", "#text") for n in frag.successors()
    ]
    # document order: div, span, i, text, b
    assert walk == ["div", "span", "i", "#text", "b"]
    assert frag.first_child().tag == "div"
    assert div.first_child().tag == "span"


def test_attribute_overwrite_remove_and_concurrent_lww():
    a, b = Doc(client_id=1), Doc(client_id=2)
    fa = a.get_xml_fragment("x")
    with a.transact() as txn:
        el = fa.insert(txn, 0, XmlElementPrelim("div", attributes={"k": "1"}))
    two_way_sync(a, b)
    eb = b.get_xml_fragment("x").first_child()
    ea = fa.first_child()
    # overwrite + remove locally
    with a.transact() as txn:
        ea.insert_attribute(txn, "k", "2")
        ea.insert_attribute(txn, "extra", "x")
    with a.transact() as txn:
        ea.remove_attribute(txn, "extra")
    two_way_sync(a, b)
    assert dict(eb.attributes()) == {"k": "2"}
    # concurrent writes to the SAME attribute: both converge to one winner
    with a.transact() as txn:
        ea.insert_attribute(txn, "k", "from-a")
    with b.transact() as txn:
        eb.insert_attribute(txn, "k", "from-b")
    two_way_sync(a, b)
    two_way_sync(a, b)
    assert dict(ea.attributes()) == dict(eb.attributes())
    assert dict(ea.attributes())["k"] in ("from-a", "from-b")


def test_concurrent_sibling_inserts_converge():
    a, b = Doc(client_id=1), Doc(client_id=2)
    fa = a.get_xml_fragment("x")
    with a.transact() as txn:
        fa.insert(txn, 0, XmlElementPrelim("anchor"))
    two_way_sync(a, b)
    fb = b.get_xml_fragment("x")
    with a.transact() as txn:
        fa.insert(txn, 1, XmlElementPrelim("from-a"))
    with b.transact() as txn:
        fb.insert(txn, 1, XmlElementPrelim("from-b"))
    two_way_sync(a, b)
    two_way_sync(a, b)
    tags_a = [getattr(k, "tag", "#text") for k in fa.children()]
    tags_b = [getattr(k, "tag", "#text") for k in fb.children()]
    assert tags_a == tags_b
    assert sorted(tags_a) == ["anchor", "from-a", "from-b"]
    assert fa.get_string() == fb.get_string()


def test_serialization_roundtrip_both_formats():
    d = Doc(client_id=1)
    frag, div = build_tree(d)
    with d.transact() as txn:
        tx = [k for k in div.children() if type(k).__name__ == "XmlText"][0]
        tx.insert(txn, 3, " node")
    want = frag.get_string()
    assert "div" in want and "span" in want and "mid node" in want
    v1 = d.encode_state_as_update_v1()
    f1 = Doc(client_id=7)
    f1.apply_update_v1(v1)
    assert f1.get_xml_fragment("x").get_string() == want
    f2 = Doc(client_id=8)
    f2.apply_update_v2(Update.decode_v1(v1).encode_v2())
    assert f2.get_xml_fragment("x").get_string() == want


def test_xml_text_formatting_inside_element():
    d = Doc(client_id=1)
    frag = d.get_xml_fragment("x")
    with d.transact() as txn:
        el = frag.insert(txn, 0, XmlElementPrelim("p"))
        el.insert(txn, 0, XmlTextPrelim("plain bold plain"))
    tx = frag.first_child().first_child()
    with d.transact() as txn:
        tx.format(txn, 6, 4, {"b": True})
    runs = tx.diff()
    assert [(r.insert, r.attributes) for r in runs] == [
        ("plain ", None),
        ("bold", {"b": True}),
        (" plain", None),
    ]
    # formatting survives the wire
    fresh = Doc(client_id=9)
    fresh.apply_update_v1(d.encode_state_as_update_v1())
    fx = fresh.get_xml_fragment("x").first_child().first_child()
    assert [(r.insert, r.attributes) for r in fx.diff()] == [
        (r.insert, r.attributes) for r in runs
    ]


def test_hook_attributes():
    from ytpu.types import XmlHookPrelim

    d = Doc(client_id=1)
    frag = d.get_xml_fragment("x")
    try:
        with d.transact() as txn:
            hook = frag.insert(txn, 0, XmlHookPrelim("component"))
    except (ImportError, AttributeError):
        pytest.skip("hook prelim not exposed")
    with d.transact() as txn:
        hook.insert_attribute(txn, "prop", "42")
    assert hook.hook_name == "component"
    assert dict(hook.attributes()) == {"prop": "42"}
