"""Raw-offsets byte ingestion (ISSUE-7 tentpole): the host ships raw
concatenated update bytes + a tiny per-update offsets table, the device
gathers the update lanes and decodes the varints itself
(`decode_kernel.gather_raw_lanes` → `replay_chunk_program_raw`), and
per-chunk host staging collapses to a memcpy (`pack_raw_updates_into`).

Coverage: raw-vs-packed byte parity through the async replay (with ≥1
mid-stream compaction), the memcpy-staging invariant (zero per-update
payload reads per chunk), depth>2 pipelining, the gathered-lane matrix's
byte identity with `pack_updates` on streams carrying LIVE MOVES and
mixed content (which pins decode parity for every content kind without
compiling a second decode program), the V2 raw pack, and deferred
decode-error message parity across all three lanes.

Every replay here reuses test_async_overlap's workload and its ONE
(n_docs=2, capacity=256, chunk=16) compiled shape family — this file
sorts immediately after it, so the decode/xla_chunk_step/compaction
programs are already warm; the two chunk programs (raw + host-packed)
are this file's only fresh big traces. The fused interpret test routes
through `tests/_fused_interpret.run_or_skip` and runs LAST.
"""

import numpy as np
import pytest

from ytpu.native import available as native_available

from _fused_interpret import run_or_skip
from test_async_overlap import CAPACITY, CHUNK, D_BLOCK, N_DOCS, _workload

needs_native = pytest.mark.skipif(
    not native_available(), reason="native codec unavailable (plan pre-scan)"
)


def _make(ingest: str, lane: str = "xla", interpret: bool = False, **kw):
    from ytpu.models.replay import FusedReplay

    _, _, plan = _workload()
    return FusedReplay(
        n_docs=N_DOCS,
        plan=plan,
        capacity=CAPACITY,
        max_capacity=CAPACITY,  # growth disabled: compaction must carry it
        d_block=D_BLOCK,
        chunk=CHUNK,
        lane=lane,
        interpret=interpret,
        overlap=True,
        ingest=ingest,
        **kw,
    )


# the access-counting payload list is shared with bench's ingest_raw
# rehearsal so the copy-only invariant cannot drift between CI and tests
from bench import _CountingList  # noqa: E402


@needs_native
def test_raw_vs_packed_byte_parity_with_compaction():
    """The raw-offsets lane must be byte-exact vs the host-packed lane
    (and the serial loop's oracle text) on a multi-chunk stream that
    trips ≥1 between-chunk compaction — slot layout permutes under
    compaction, so the decoded text is the byte-exact surface."""
    log, expect, _ = _workload()
    raw = _make(ingest="raw")
    s_raw = raw.run(log)
    packed = _make(ingest="packed")
    s_packed = packed.run(log)
    assert s_raw.ingest == "raw" and s_packed.ingest == "packed"
    assert s_raw.compactions >= 1 and s_packed.compactions >= 1
    assert s_raw.growths == 0, s_raw  # pins the shape-reuse property
    assert s_raw.chunks == s_packed.chunks
    for d in range(N_DOCS):
        assert raw.get_string(d) == packed.get_string(d) == expect
    # the raw lane actually staged the stream's bytes (payload bytes +
    # one EMPTY_UPDATE tail marker per chunk)
    wire_bytes = sum(len(p) for p in log)
    assert s_raw.stage_bytes == wire_bytes + 2 * s_raw.chunks, s_raw
    assert s_packed.stage_bytes == wire_bytes, s_packed


@needs_native
def test_raw_staging_is_copy_only():
    """The memcpy-staging invariant: after the one-time wire-table build
    (an O(bytes) join), per-chunk raw staging performs ZERO per-update
    payload reads — asserted structurally with a counting list, not a
    timer, so it cannot rot into a flaky benchmark."""
    log, expect, _ = _workload()
    counted = _CountingList(log)
    rep = _make(ingest="raw")
    rep.run(counted)
    assert counted.item_reads == 0, (
        f"raw staging read {counted.item_reads} payload items"
    )
    assert rep.get_string(0) == expect


@needs_native
def test_raw_depth3_pipeline():
    """Depth > 2 (free under raw staging): three preallocated raw slots,
    the in-flight cap held at 3, every later chunk re-packing a
    recycled slot — with byte parity."""
    from ytpu.models.replay import plan_overlap

    log, expect, _ = _workload()
    rep = _make(ingest="raw", depth=3)
    op = rep.overlap_plan()
    assert op == plan_overlap(len(log), CHUNK, depth=3)
    assert op.depth == 3 and op.buffers == 3
    stats = rep.run(log)
    assert rep.get_string(0) == expect
    assert 1 <= stats.max_inflight <= 3, stats
    assert stats.buffer_reuses == stats.chunks - 3, stats


@needs_native
def test_gather_raw_lanes_matches_pack_updates_with_moves():
    """The device lane-gather materializes a byte-IDENTICAL matrix to
    host `pack_updates` — including the zero mask past each lane's
    length that the decoder's prefix sums and gather guard read. Driven
    on a stream with LIVE MOVES, map rows, and Any content, this pins
    raw-vs-packed decode parity for every content kind the V1 decoder
    supports without compiling a second decode program."""
    import jax.numpy as jnp

    from ytpu.core import Doc
    from ytpu.models.replay import build_wire_table, raw_chunk_cap
    from ytpu.ops.decode_kernel import (
        gather_raw_lanes,
        pack_raw_updates_into,
        pack_updates,
    )

    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    arr = doc.get_array("a")
    with doc.transact() as txn:
        for v in range(12):
            arr.push_back(txn, v)
    for r in range(4):
        with doc.transact() as txn:
            arr.move_range_to(txn, 1, 3, len(arr) - 1)  # live moves
        with doc.transact() as txn:
            arr.insert(txn, 2, {"k": 100 + r})  # map-shaped Any content
        with doc.transact() as txn:
            arr.remove_range(txn, 3, 2)
    width = max(len(p) for p in log) + 16
    buf, lens = pack_updates(log, pad_to=width)
    wire, woffs = build_wire_table(log)
    chunk = len(log)
    cap = raw_chunk_cap(woffs, chunk)
    raw = np.zeros(cap, dtype=np.uint8)
    offs = np.zeros(chunk, dtype=np.int32)
    rlens = np.zeros(chunk, dtype=np.int32)
    pack_raw_updates_into(wire, woffs, 0, chunk, raw, offs, rlens, width=width)
    assert rlens.tolist() == lens.tolist()
    gathered = np.asarray(
        gather_raw_lanes(
            jnp.asarray(raw), jnp.asarray(offs), jnp.asarray(rlens), width
        )
    )
    assert (gathered == buf).all(), "gathered lane matrix != host-packed"
    # a short tail chunk decodes as EMPTY_UPDATE at the compiled shape
    pack_raw_updates_into(
        wire, woffs, 1, chunk, raw, offs, rlens, width=width
    )
    assert rlens[chunk - 1] == 2 and offs[chunk - 1] == int(
        woffs[chunk] - woffs[1]
    )
    with pytest.raises(ValueError, match="exceeds staging width"):
        pack_raw_updates_into(
            wire, woffs, 0, chunk, raw, offs, rlens, width=8
        )
    with pytest.raises(ValueError, match="exceeds staging capacity"):
        pack_raw_updates_into(
            wire, woffs, 0, chunk, raw[:8], offs, rlens, width=width
        )


@needs_native
def test_pack_updates_v2_raw_matches_packed():
    """The V2 raw pack ships the same bytes the padded V2 matrix holds:
    gathering the flat arena at the staged row extents reproduces
    `pack_updates_v2`'s matrix byte-for-byte (cold sidecars included —
    their refs point PAST the payload length, so the gather mask uses
    the staged extent, not the decode length)."""
    import jax.numpy as jnp

    from ytpu.core import Doc, Update
    from ytpu.ops.decode_kernel import gather_raw_lanes
    from ytpu.ops.decode_v2 import pack_updates_v2, pack_updates_v2_raw

    doc = Doc(client_id=5)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text("text")
    for i in range(4):
        with doc.transact() as txn:
            txt.insert(txn, i, "abcd"[i])
    with doc.transact() as txn:
        # Format content is a COLD kind: exercises the sidecar extent
        txt.format(txn, 0, 2, {"bold": True})
    v2 = [Update.decode_v1(p).encode_v2() for p in log]
    buf, lens, spans, side = pack_updates_v2(v2)
    wire, offs, row_lens, rlens, rspans, rside, width = pack_updates_v2_raw(v2)
    assert width == buf.shape[1]
    assert rlens.tolist() == lens.tolist()
    assert (rspans == spans).all()
    assert (side is None) == (rside is None)
    if side is not None:
        assert (rside == side).all()
    gathered = np.asarray(
        gather_raw_lanes(
            jnp.asarray(wire),
            jnp.asarray(offs),
            jnp.asarray(row_lens),
            width,
        )
    )
    assert (gathered == buf).all(), "V2 gathered matrix != host-packed"


@needs_native
def test_raw_deferred_decode_error_exact_message_parity():
    """A truncated update through the raw lane surfaces DEFERRED (the
    on-device varint decode ORs its flags into the sticky scalar) but
    the host re-identification must raise the serial loop's EXACT
    message — same contract as the packed lane (satellite of ISSUE-7)."""
    from ytpu.models.replay import FusedReplay

    log, _, plan = _workload()
    bad = list(log)
    bad[23] = bad[23][: len(bad[23]) // 2]  # truncation → FLAG_MALFORMED
    serial = FusedReplay(
        n_docs=N_DOCS, plan=plan, capacity=CAPACITY, max_capacity=CAPACITY,
        d_block=D_BLOCK, chunk=CHUNK, lane="xla",
    )
    with pytest.raises(RuntimeError, match="flagged updates") as serial_err:
        serial.run(bad)
    with pytest.raises(RuntimeError, match="flagged updates") as raw_err:
        _make(ingest="raw").run(bad)
    with pytest.raises(RuntimeError, match="flagged updates") as packed_err:
        _make(ingest="packed").run(bad)
    assert str(raw_err.value) == str(serial_err.value) == str(packed_err.value)
    assert "[23]" in str(raw_err.value)


@needs_native
def test_raw_ingest_dry_run_contract():
    """bench's host-only raw-ingest rehearsal: copy-only staging,
    depth-3 plan held, and the staging speedup recorded (the CI guard
    that catches a staging regression before a device round)."""
    import bench as _bench

    log, _, _ = _workload()
    out = _bench.ingest_raw_dry_run(log[: 6 * CHUNK], chunk=CHUNK, depth=3)
    assert out["copy_only_staging"] is True
    assert out["depth"] == 3 and out["buffers"] == 3
    assert out["n_chunks"] == 6 and out["max_inflight"] <= 3
    assert out["stage_speedup_vs_packed"] > 1.5
    assert out["stage_bytes_per_s"] > 0
    assert 0.0 <= out["stall_fraction"] <= 1.0


@needs_native
def test_raw_fused_interpret_or_skip():
    """The fused Pallas lane fed by the raw chunk program — or a SKIP
    when this container's jax cannot interpret the kernel (memoized
    across files by tests/_fused_interpret)."""
    log, _, _ = _workload()
    prefix = log[: 2 * CHUNK]
    oracle = _make(ingest="packed")
    oracle.run(prefix)
    rep = _make(ingest="raw", lane="fused", interpret=True)
    run_or_skip(lambda: rep.run(prefix))
    for d in range(N_DOCS):
        assert rep.get_string(d) == oracle.get_string(d)
