"""Nested branch trees on the device engine vs the host oracle.

Nested shared types live in the same block table: a ContentType row owns a
child sequence through its `head` column; children reference it through the
`parent` column (parity: block.rs:503-523 TypePtr resolution + the Branch
projections of branch.rs:173-215).
"""

import random

import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    get_tree,
    init_state,
)
from ytpu.types.shared import ArrayPrelim, MapPrelim, TextPrelim


def device_tree_from_docs(docs, root="r", capacity=256):
    enc = BatchEncoder(root_name=root)
    updates = [Update.decode_v1(d.encode_state_as_update_v1()) for d in docs]
    batch = enc.build_batch(updates)
    state = init_state(len(docs), capacity)
    state = apply_update_batch(state, batch, enc.interner.rank_table())
    return state, enc


def test_nested_types_in_array():
    doc = Doc(client_id=1)
    arr = doc.get_array("r")
    with doc.transact() as txn:
        arr.insert_range(txn, 0, [1, "s"])
        arr.insert(txn, 2, TextPrelim("ab"))
        arr.insert(txn, 3, MapPrelim({"x": 5}))
        arr.insert(txn, 4, ArrayPrelim([2, 3]))

    state, enc = device_tree_from_docs([doc])
    assert int(state.error[0]) == 0
    tree = get_tree(state, 0, enc.payloads, enc.keys)
    assert tree["seq"] == [1, "s", "ab", {"x": 5}, [2, 3]]
    assert tree["map"] == {}
    assert doc.get_array("r").to_json() == [1, "s", "ab", {"x": 5}, [2, 3]]


def test_nested_edits_after_creation():
    """Edits to a nested text/map arrive as separate updates whose parents
    are branch ids — the device resolves them through the parent column."""
    doc = Doc(client_id=1)
    arr = doc.get_array("r")
    with doc.transact() as txn:
        arr.insert(txn, 0, TextPrelim("base"))
        arr.insert(txn, 1, MapPrelim({}))
    with doc.transact() as txn:
        nested_text = arr.get(0)
        nested_text.insert(txn, 4, "-tail")
        nested_map = arr.get(1)
        nested_map.insert(txn, "k", 9)
        nested_map.insert(txn, "k", 10)  # overwrite inside nested map

    state, enc = device_tree_from_docs([doc])
    assert int(state.error[0]) == 0
    tree = get_tree(state, 0, enc.payloads, enc.keys)
    assert tree["seq"] == ["base-tail", {"k": 10}]
    assert doc.get_array("r").to_json() == ["base-tail", {"k": 10}]


def test_nested_concurrent_edits():
    """Two clients edit the same nested text concurrently."""
    a = Doc(client_id=1)
    with a.transact() as txn:
        a.get_array("r").insert(txn, 0, TextPrelim("mid"))
    b = Doc(client_id=2)
    b.apply_update_v1(a.encode_state_as_update_v1())

    with a.transact() as txn:
        a.get_array("r").get(0).insert(txn, 0, "L-")
    with b.transact() as txn:
        b.get_array("r").get(0).insert(txn, 3, "-R")
    ua, ub = a.encode_state_as_update_v1(), b.encode_state_as_update_v1()
    a.apply_update_v1(ub)
    b.apply_update_v1(ua)
    expected = a.get_array("r").to_json()
    assert b.get_array("r").to_json() == expected
    assert expected == ["L-mid-R"]

    state, enc = device_tree_from_docs([a, b])
    for d in range(2):
        assert int(state.error[d]) == 0
        assert get_tree(state, d, enc.payloads, enc.keys)["seq"] == expected


def test_deleted_nested_type_not_rendered():
    doc = Doc(client_id=1)
    arr = doc.get_array("r")
    with doc.transact() as txn:
        arr.insert(txn, 0, TextPrelim("gone"))
        arr.insert(txn, 1, 42)
    with doc.transact() as txn:
        arr.remove(txn, 0)

    state, enc = device_tree_from_docs([doc])
    assert int(state.error[0]) == 0
    assert get_tree(state, 0, enc.payloads, enc.keys)["seq"] == [42]


@pytest.mark.parametrize("seed", [7, 8, 9])
def test_tree_fuzz_parity(seed):
    """Random nested edits across 2 clients with partial syncs."""
    rng = random.Random(seed)
    docs = [Doc(client_id=10 + i) for i in range(2)]
    # both start from a shared skeleton: [text, map]
    with docs[0].transact() as txn:
        docs[0].get_array("r").insert(txn, 0, TextPrelim("seed"))
        docs[0].get_array("r").insert(txn, 1, MapPrelim({}))
    docs[1].apply_update_v1(docs[0].encode_state_as_update_v1())

    from ytpu.types.map import Map
    from ytpu.types.text import Text

    def find(arr, cls):
        for i in range(len(arr.to_json())):
            v = arr.get(i)
            if isinstance(v, cls):
                return v
        return None

    for step in range(14):
        d = rng.choice(docs)
        arr = d.get_array("r")
        with d.transact() as txn:
            roll = rng.random()
            t = find(arr, Text)
            m = find(arr, Map)
            if roll < 0.4 and t is not None:
                t.insert(txn, rng.randrange(t.branch.content_len + 1), "x")
            elif roll < 0.7 and m is not None:
                m.insert(txn, rng.choice("ab"), rng.randrange(100))
            else:
                arr.insert(txn, rng.randrange(len(arr.to_json()) + 1), step)
        if rng.random() < 0.5:
            x, y = rng.sample(docs, 2)
            y.apply_update_v1(x.encode_state_as_update_v1(y.state_vector()))

    for x in docs:
        for y in docs:
            if x is not y:
                y.apply_update_v1(x.encode_state_as_update_v1(y.state_vector()))
    expected = docs[0].get_array("r").to_json()
    assert docs[1].get_array("r").to_json() == expected

    state, enc = device_tree_from_docs(docs)
    for d in range(2):
        assert int(state.error[d]) == 0, f"doc {d} error {int(state.error[d])}"
        assert get_tree(state, d, enc.payloads, enc.keys)["seq"] == expected
