"""Weak links / quotations (model: reference types/weak.rs tests)."""

from ytpu.core import Doc
from ytpu.types import map_link, quote_range


def test_map_link_deref():
    d = Doc(client_id=1)
    m = d.get_map("m")
    target = d.get_map("data")
    with d.transact() as txn:
        target.insert(txn, "k", "value1")
    link = map_link(target, "k")
    with d.transact() as txn:
        m.insert(txn, "ref", link)
    ref = m.get("ref")
    assert ref.try_deref() == "value1"


def test_map_link_follows_overwrites():
    d = Doc(client_id=1)
    m = d.get_map("m")
    data = d.get_map("data")
    with d.transact() as txn:
        data.insert(txn, "k", "old")
    with d.transact() as txn:
        m.insert(txn, "ref", map_link(data, "k"))
    with d.transact() as txn:
        data.insert(txn, "k", "new")
    assert m.get("ref").try_deref() == "new"


def test_map_link_cleared_on_delete():
    d = Doc(client_id=1)
    m = d.get_map("m")
    data = d.get_map("data")
    with d.transact() as txn:
        data.insert(txn, "k", "val")
    with d.transact() as txn:
        m.insert(txn, "ref", map_link(data, "k"))
    with d.transact() as txn:
        data.remove(txn, "k")
    assert m.get("ref").try_deref() is None


def test_array_quote_unquote():
    d = Doc(client_id=1)
    arr = d.get_array("a")
    m = d.get_map("m")
    with d.transact() as txn:
        arr.insert_range(txn, 0, [10, 20, 30, 40, 50])
    with d.transact() as txn:
        q = quote_range(arr, txn, 1, 3)
        m.insert(txn, "q", q)
    assert m.get("q").unquote() == [20, 30, 40]


def test_quote_survives_sync():
    a, b = Doc(client_id=1), Doc(client_id=2)
    arr_a = a.get_array("a")
    map_a = a.get_map("m")
    with a.transact() as txn:
        arr_a.insert_range(txn, 0, ["x", "y", "z"])
    with a.transact() as txn:
        map_a.insert(txn, "q", quote_range(arr_a, txn, 0, 2))
    b.apply_update_v1(a.encode_state_as_update_v1())
    ref = b.get_map("m").get("q")
    assert ref.unquote() == ["x", "y"]


def test_weak_link_observer_fires_on_target_change():
    d = Doc(client_id=1)
    m = d.get_map("m")
    data = d.get_map("data")
    with d.transact() as txn:
        data.insert(txn, "k", "v0")
    with d.transact() as txn:
        m.insert(txn, "ref", map_link(data, "k"))
    ref = m.get("ref")
    fired = []
    ref.observe(lambda txn, event: fired.append(event))
    with d.transact() as txn:
        data.insert(txn, "k", "v1")
    assert fired, "link observer should fire when the target entry changes"


def test_quote_spans_moved_range():
    """Quotation follows DOCUMENT order (reference weak.rs:638
    `RangeIter<MoveIter>`): elements moved into the quoted span appear,
    elements moved out vanish."""
    d = Doc(client_id=1)
    arr = d.get_array("a")
    m = d.get_map("m")
    with d.transact() as txn:
        arr.insert_range(txn, 0, [10, 20, 30, 40, 50])
    with d.transact() as txn:
        m.insert(txn, "q", quote_range(arr, txn, 1, 3))  # [20, 30, 40]
    # move 50 INTO the quoted span (before 30)
    with d.transact() as txn:
        arr.move_to(txn, 4, 2)
    assert arr.to_json() == [10, 20, 50, 30, 40]
    assert m.get("q").unquote() == [20, 50, 30, 40]
    # move 30 OUT of the span (to the front)
    with d.transact() as txn:
        arr.move_to(txn, 3, 0)
    assert arr.to_json() == [30, 10, 20, 50, 40]
    assert m.get("q").unquote() == [20, 50, 40]


def test_quote_moved_range_survives_sync():
    """The move-aware quotation renders identically on a synced replica."""
    a, b = Doc(client_id=1), Doc(client_id=2)
    arr = a.get_array("a")
    m = a.get_map("m")
    with a.transact() as txn:
        arr.insert_range(txn, 0, [1, 2, 3, 4])
    with a.transact() as txn:
        m.insert(txn, "q", quote_range(arr, txn, 0, 2))  # [1, 2]
    with a.transact() as txn:
        arr.move_to(txn, 3, 1)  # 4 moves inside: [1, 4, 2, 3]
    b.apply_update_v1(a.encode_state_as_update_v1())
    assert b.get_array("a").to_json() == [1, 4, 2, 3]
    assert a.get_map("m").get("q").unquote() == [1, 4, 2]
    assert b.get_map("m").get("q").unquote() == [1, 4, 2]


def test_deleting_link_unlinks_targets():
    """Deleting the weak link removes its back-references: later edits to
    the old target no longer notify the (dead) link (weak.rs:509)."""
    d = Doc(client_id=1)
    arr = d.get_array("a")
    m = d.get_map("m")
    with d.transact() as txn:
        arr.insert_range(txn, 0, ["a", "b", "c"])
    with d.transact() as txn:
        m.insert(txn, "q", quote_range(arr, txn, 0, 3))
    store = d.store
    assert any(store.linked_by.values())
    with d.transact() as txn:
        m.remove(txn, "q")
    assert not store.linked_by  # back-refs gone
    # edits to the former targets neither crash nor resurrect the link
    with d.transact() as txn:
        arr.insert(txn, 1, "x")
    assert arr.to_json() == ["a", "x", "b", "c"]


def test_deep_observation_through_link():
    """Changes to quoted content surface through the link: deletions of
    linked items notify the link's observers (transaction.rs:634-647),
    and in-range inserts appear in the next unquote (the range is
    bounded by sticky ids, not a snapshot)."""
    d = Doc(client_id=1)
    txt = d.get_text("t")
    m = d.get_map("m")
    with d.transact() as txn:
        txt.insert(txn, 0, "hello world")
    with d.transact() as txn:
        m.insert(txn, "q", quote_range(txt, txn, 0, 5))  # "hello"
    ref = m.get("q")
    # in-range insert: content flows into the quotation
    with d.transact() as txn:
        txt.insert(txn, 2, "XY")
    assert "".join(ref.unquote()) == "heXYllo"
    # deleting linked content notifies the link branch
    fired = []
    d.observe_after_transaction(lambda txn: fired.append(
        any(b is ref.branch for b in txn.changed)
    ))
    with d.transact() as txn:
        txt.remove_range(txn, 0, 2)  # inside the quoted range
    assert fired and fired[-1], "link not notified of in-range delete"
    # an edit far outside the range must NOT notify the link
    fired.clear()
    with d.transact() as txn:
        txt.insert(txn, len(txt), "!")
    assert fired and not fired[-1]


def test_quote_roundtrip_v1_v2():
    """Weak links survive both wire formats byte-compatibly."""
    from ytpu.core import Update

    d = Doc(client_id=1)
    arr = d.get_array("a")
    m = d.get_map("m")
    with d.transact() as txn:
        arr.insert_range(txn, 0, [7, 8, 9])
    with d.transact() as txn:
        m.insert(txn, "q", quote_range(arr, txn, 1, 2))
    v1 = d.encode_state_as_update_v1()
    for payload, fmt in ((v1, "v1"), (Update.decode_v1(v1).encode_v2(), "v2")):
        fresh = Doc(client_id=9)
        if fmt == "v1":
            fresh.apply_update_v1(payload)
        else:
            fresh.apply_update_v2(payload)
        assert fresh.get_map("m").get("q").unquote() == [8, 9], fmt


def test_overlapping_quotes_share_targets():
    """Two links quoting overlapping ranges both track edits; deleting
    one leaves the other's back-refs intact."""
    d = Doc(client_id=1)
    arr = d.get_array("a")
    m = d.get_map("m")
    with d.transact() as txn:
        arr.insert_range(txn, 0, [1, 2, 3, 4, 5])
    with d.transact() as txn:
        m.insert(txn, "q1", quote_range(arr, txn, 0, 3))  # [1,2,3]
        m.insert(txn, "q2", quote_range(arr, txn, 2, 3))  # [3,4,5]
    assert m.get("q1").unquote() == [1, 2, 3]
    assert m.get("q2").unquote() == [3, 4, 5]
    with d.transact() as txn:
        m.remove(txn, "q1")
    assert m.get("q2").unquote() == [3, 4, 5]
    with d.transact() as txn:
        arr.remove_range(txn, 3, 1)  # delete 4 (inside q2)
    assert m.get("q2").unquote() == [3, 5]
