"""Weak links / quotations (model: reference types/weak.rs tests)."""

from ytpu.core import Doc
from ytpu.types import map_link, quote_range


def test_map_link_deref():
    d = Doc(client_id=1)
    m = d.get_map("m")
    target = d.get_map("data")
    with d.transact() as txn:
        target.insert(txn, "k", "value1")
    link = map_link(target, "k")
    with d.transact() as txn:
        m.insert(txn, "ref", link)
    ref = m.get("ref")
    assert ref.try_deref() == "value1"


def test_map_link_follows_overwrites():
    d = Doc(client_id=1)
    m = d.get_map("m")
    data = d.get_map("data")
    with d.transact() as txn:
        data.insert(txn, "k", "old")
    with d.transact() as txn:
        m.insert(txn, "ref", map_link(data, "k"))
    with d.transact() as txn:
        data.insert(txn, "k", "new")
    assert m.get("ref").try_deref() == "new"


def test_map_link_cleared_on_delete():
    d = Doc(client_id=1)
    m = d.get_map("m")
    data = d.get_map("data")
    with d.transact() as txn:
        data.insert(txn, "k", "val")
    with d.transact() as txn:
        m.insert(txn, "ref", map_link(data, "k"))
    with d.transact() as txn:
        data.remove(txn, "k")
    assert m.get("ref").try_deref() is None


def test_array_quote_unquote():
    d = Doc(client_id=1)
    arr = d.get_array("a")
    m = d.get_map("m")
    with d.transact() as txn:
        arr.insert_range(txn, 0, [10, 20, 30, 40, 50])
    with d.transact() as txn:
        q = quote_range(arr, txn, 1, 3)
        m.insert(txn, "q", q)
    assert m.get("q").unquote() == [20, 30, 40]


def test_quote_survives_sync():
    a, b = Doc(client_id=1), Doc(client_id=2)
    arr_a = a.get_array("a")
    map_a = a.get_map("m")
    with a.transact() as txn:
        arr_a.insert_range(txn, 0, ["x", "y", "z"])
    with a.transact() as txn:
        map_a.insert(txn, "q", quote_range(arr_a, txn, 0, 2))
    b.apply_update_v1(a.encode_state_as_update_v1())
    ref = b.get_map("m").get("q")
    assert ref.unquote() == ["x", "y"]


def test_weak_link_observer_fires_on_target_change():
    d = Doc(client_id=1)
    m = d.get_map("m")
    data = d.get_map("data")
    with d.transact() as txn:
        data.insert(txn, "k", "v0")
    with d.transact() as txn:
        m.insert(txn, "ref", map_link(data, "k"))
    ref = m.get("ref")
    fired = []
    ref.observe(lambda txn, event: fired.append(event))
    with d.transact() as txn:
        data.insert(txn, "k", "v1")
    assert fired, "link observer should fire when the target entry changes"
