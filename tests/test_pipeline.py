"""Decode->integrate pipeline parity (PP axis; SURVEY §2 parallelism table)."""

import random

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    get_string,
    get_tree,
    init_state,
)
from ytpu.models.pipeline import UpdatePipeline


def make_payload_stream(n_txns=40, seed=5):
    """A realistic per-transaction update stream from two host clients."""
    rng = random.Random(seed)
    a, b = Doc(client_id=1), Doc(client_id=2)
    payloads = []
    for d in (a, b):
        d.observe_update_v1(lambda p, origin, txn: payloads.append(p))
    for i in range(n_txns):
        d = rng.choice((a, b))
        t = d.get_text("t")
        with d.transact() as txn:
            pos = rng.randrange(t.branch.content_len + 1)
            if rng.random() < 0.8 or t.branch.content_len == 0:
                t.insert(txn, pos, f"w{i} ")
            else:
                t.remove_range(txn, 0, min(2, t.branch.content_len))
        # immediate full sync keeps both clients' updates causally ordered
        other = b if d is a else a
        other.apply_update_v1(d.encode_state_as_update_v1(other.state_vector()))
    assert a.get_text("t").get_string() == b.get_text("t").get_string()
    return payloads, a.get_text("t").get_string()


def test_pipeline_matches_direct_path():
    payloads, expected = make_payload_stream()
    enc = BatchEncoder(root_name="t")
    pipe = UpdatePipeline(enc, n_rows=8, n_dels=4, chunk_steps=8)
    state, chunks = pipe.run(init_state(4, 512), payloads)
    assert chunks >= len(payloads) // 8
    assert int(max(state.error.tolist())) == 0
    for d in range(4):
        assert get_string(state, d, enc.payloads) == expected

    # same result as the one-batch-at-a-time direct path
    enc2 = BatchEncoder(root_name="t")
    state2 = init_state(4, 512)
    for p in payloads:
        batch = enc2.build_batch([Update.decode_v1(p)] * 4)
        state2 = apply_update_batch(state2, batch, enc2.interner.rank_table())
    for d in range(4):
        assert get_string(state2, d, enc2.payloads) == expected


def test_pipeline_tail_chunk_padding():
    """Payload count not divisible by chunk_steps still integrates fully."""
    payloads, expected = make_payload_stream(n_txns=13, seed=6)
    enc = BatchEncoder(root_name="t")
    pipe = UpdatePipeline(enc, n_rows=8, n_dels=4, chunk_steps=5)
    state, chunks = pipe.run(init_state(2, 256), payloads)
    assert chunks == (len(payloads) + 4) // 5
    assert int(max(state.error.tolist())) == 0
    assert get_string(state, 0, enc.payloads) == expected


def test_pipeline_decode_error_surfaces():
    enc = BatchEncoder(root_name="t")
    pipe = UpdatePipeline(enc, n_rows=8, n_dels=4, chunk_steps=4)
    import pytest

    with pytest.raises(Exception):
        pipe.run(init_state(1, 64), [b"\xff\xff\xff garbage"])
