"""lib0 encoding round-trips (model: proptest round-trips at
reference encoding/mod.rs:33-42 and any.rs tests)."""

import random

import pytest

from ytpu.encoding.lib0 import Cursor, Undefined, Writer, read_any, write_any


def roundtrip_uint(v):
    w = Writer()
    w.write_var_uint(v)
    return Cursor(w.to_bytes()).read_var_uint()


def roundtrip_int(v):
    w = Writer()
    w.write_var_int(v)
    return Cursor(w.to_bytes()).read_var_int()


def test_var_uint_roundtrip():
    for v in [0, 1, 127, 128, 129, 16383, 16384, 2**31, 2**53, 2**64 - 1]:
        assert roundtrip_uint(v) == v
    rng = random.Random(42)
    for _ in range(1000):
        v = rng.getrandbits(rng.randint(1, 64))
        assert roundtrip_uint(v) == v


def test_var_uint_wire_bytes():
    # 7-bit little-endian groups with continuation bit
    w = Writer()
    w.write_var_uint(0x80)
    assert w.to_bytes() == bytes([0x80, 0x01])
    w = Writer()
    w.write_var_uint(300)
    assert w.to_bytes() == bytes([0xAC, 0x02])


def test_var_int_roundtrip():
    for v in [0, -1, 1, 63, -63, 64, -64, 2**31, -(2**31), 2**53 - 1, -(2**53 - 1)]:
        assert roundtrip_int(v) == v
    rng = random.Random(7)
    for _ in range(1000):
        v = rng.getrandbits(rng.randint(1, 53)) * rng.choice([1, -1])
        assert roundtrip_int(v) == v


def test_var_int_sign_bit():
    # -1 encodes sign in bit 0x40 of the first byte
    w = Writer()
    w.write_var_int(-1)
    assert w.to_bytes() == bytes([0x41])
    w = Writer()
    w.write_var_int(1)
    assert w.to_bytes() == bytes([0x01])


def test_string_roundtrip():
    for s in ["", "hello", "héllo wörld", "日本語", "🌍🚀", "a" * 1000]:
        w = Writer()
        w.write_string(s)
        assert Cursor(w.to_bytes()).read_string() == s


def test_any_roundtrip():
    samples = [
        None,
        Undefined,
        True,
        False,
        0,
        1,
        -1,
        2**53 - 1,
        -(2**53 - 1),
        2**60,  # bigint territory
        0.5,
        -3.25,
        1e300,
        "text",
        b"\x00\x01\x02",
        [1, "two", None, [3.5]],
        {"a": 1, "b": [True, {"c": None}]},
    ]
    for v in samples:
        w = Writer()
        write_any(w, v)
        cur = Cursor(w.to_bytes())
        out = read_any(cur)
        assert out == v or (v is Undefined and out is Undefined), (v, out)
        assert not cur.has_content()


def test_any_integer_float_tags():
    # ints in safe range use tag 125; float 3.0 collapses to integer (JS semantics)
    w = Writer()
    write_any(w, 3.0)
    assert w.to_bytes()[0] == 125
    w = Writer()
    write_any(w, 3.5)
    assert w.to_bytes()[0] == 124  # exactly representable in f32
    w = Writer()
    write_any(w, 1.1)
    assert w.to_bytes()[0] == 123  # needs f64


def test_truncated_input_raises():
    from ytpu.encoding.lib0 import EncodingError

    with pytest.raises(EncodingError):
        Cursor(b"\x80").read_var_uint()
    with pytest.raises(EncodingError):
        Cursor(b"\x05abc").read_string()
