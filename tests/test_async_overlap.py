"""Async double-buffered replay (ISSUE-5 tentpole): the overlap lane
(`FusedReplay(overlap=True)` → `PackedReplayDriver.step_bytes` → the one
fused decode→rebase→integrate `replay_chunk_program`) vs the synchronous
chunked loop, on CPU-testable shapes.

Every test in this file shares ONE workload/plan and the (n_docs=2,
capacity=256, chunk=16) shape family, so each compiled program (decode,
xla_chunk_step, replay_chunk_program, compact_packed) is traced at most
once for the whole file — distinct big programs are the suite's scarce
resource (conftest.py LLVM-arena note). The fused-lane interpret test
routes through `tests/_fused_interpret.run_or_skip` (this container's
jax cannot interpret the Pallas kernel — seed behavior) and runs LAST so
the cheap assertions report first.
"""

from functools import lru_cache

import numpy as np
import pytest

from ytpu.native import available as native_available

from _fused_interpret import run_or_skip

needs_native = pytest.mark.skipif(
    not native_available(), reason="native codec unavailable (plan pre-scan)"
)

# (n_docs, capacity, chunk, d_block) — the one shape family of this file
N_DOCS, CAPACITY, CHUNK, D_BLOCK = 2, 256, 16, 2


@lru_cache(maxsize=1)
def _workload():
    """Append-typing + tail erase: tombstones are clock- AND sequence-
    contiguous, so `compact_packed` actually reclaims them and a
    max_capacity == capacity replay is carried by compaction alone."""
    import bench as _bench

    ops = []
    length = 0
    for _ in range(14):
        for i in range(20):
            ops.append(("i", length, "abcdef"[i % 6]))
            length += 1
        ops.append(("d", length - 18, 18))
        length -= 18
    log, expect = _bench.build_updates(ops)
    from ytpu.models.replay import plan_replay

    return log, expect, plan_replay(log)


def _make(overlap: bool, lane: str = "xla", interpret: bool = False):
    from ytpu.models.replay import FusedReplay

    _, _, plan = _workload()
    return FusedReplay(
        n_docs=N_DOCS,
        plan=plan,
        capacity=CAPACITY,
        max_capacity=CAPACITY,  # growth disabled: compaction must carry it
        d_block=D_BLOCK,
        chunk=CHUNK,
        lane=lane,
        interpret=interpret,
        overlap=overlap,
    )


@needs_native
def test_async_parity_with_compaction_midstream():
    """The async lane must be byte-exact vs the synchronous loop on a
    multi-chunk stream that trips ≥1 between-chunk compaction — the
    decoded text (slot layout permutes under compaction) is the
    byte-exact surface, as in test_replay_chunked."""
    log, expect, _ = _workload()
    sync = _make(overlap=False)
    s_sync = sync.run(log)
    asyn = _make(overlap=True)
    s_async = asyn.run(log)
    assert s_sync.compactions >= 1 and s_async.compactions >= 1
    assert s_async.growths == 0, s_async  # pins the shape-reuse property
    assert s_async.chunks == s_sync.chunks == (len(log) + CHUNK - 1) // CHUNK
    for d in range(N_DOCS):
        assert asyn.get_string(d) == sync.get_string(d) == expect
    # double-buffer contract: depth capped at 2, every later chunk
    # re-packs a recycled slot, and the loop never synced once per chunk
    assert 1 <= s_async.max_inflight <= 2, s_async
    assert s_async.buffer_reuses == s_async.chunks - 2, s_async
    assert s_async.syncs < s_async.chunks, s_async
    assert s_async.overlap_ratio >= 0.0


@needs_native
def test_async_zero_sync_steady_state():
    """Acceptance: the steady-state async loop performs NO blocking
    device sync per chunk. On a prefix whose optimistic adds-bound never
    trips the watermark, the ONLY host materialization is the single
    drain at `finish()` — counted via the phases instrumentation
    (`replay.readout` d2h bytes = 12 per [3]-word readout, all of them
    landing in one drain) and the driver's `syncs` counter."""
    from ytpu.utils.phases import phases

    log, _, _ = _workload()
    prefix = log[: 3 * CHUNK]  # adds-bound stays far under the watermark
    sync = _make(overlap=False)
    sync.run(prefix)
    phases.reset()
    phases.enable()
    try:
        asyn = _make(overlap=True)
        stats = asyn.run(prefix)
        snap = phases.snapshot()
    finally:
        phases.disable()
        phases.reset()
    assert stats.chunks == 3 and stats.compactions == 0, stats
    assert stats.syncs == 1, f"steady state must drain once, got {stats}"
    # all 3 readouts materialized together in that one finish() drain
    assert snap["replay.readout"]["d2h_bytes"] == 12 * stats.chunks, snap
    # the overlap gauges landed in bench-visible phases
    assert "value" in snap["replay.overlap_ratio"]
    assert snap["replay.inflight_depth"]["value"] >= 1
    assert snap["replay.stage"]["calls"] == stats.chunks
    for d in range(N_DOCS):
        assert asyn.get_string(d) == sync.get_string(d)


@needs_native
def test_async_deferred_decode_error_same_message():
    """A decode error in the async lane surfaces DEFERRED (sticky device
    scalar, drained at a watermark trip or finish) but re-identifies the
    offending update host-side and raises the SAME message the serial
    loop produces at the offending chunk."""
    log, _, _ = _workload()
    bad = list(log)
    bad[37] = bad[37][: len(bad[37]) // 2]  # truncation → FLAG_MALFORMED
    with pytest.raises(RuntimeError, match="flagged updates") as sync_err:
        _make(overlap=False).run(bad)
    with pytest.raises(RuntimeError, match="flagged updates") as async_err:
        _make(overlap=True).run(bad)
    assert str(async_err.value) == str(sync_err.value)
    assert "[37]" in str(async_err.value)


@needs_native
def test_overlap_plan_and_dry_run():
    """The static staging plan (depth-2 double buffer, every later chunk
    a slot reuse) plus the host-only bench rehearsal that CI asserts
    before a device round trusts the overlap lane."""
    import bench as _bench
    from ytpu.models.replay import plan_overlap

    log, _, _ = _workload()
    op = plan_overlap(len(log), CHUNK)
    assert op.depth == 2 and op.buffers == 2
    assert op.n_chunks == (len(log) + CHUNK - 1) // CHUNK
    assert op.buffer_reuses == max(0, op.n_chunks - 2)
    assert _make(overlap=True).overlap_plan() == op
    # bench's rehearsal asserts depth/reuse internally and models the win
    out = _bench.overlap_dry_run(log[: 4 * CHUNK], chunk=CHUNK)
    assert out["depth"] == 2 and out["buffers"] == 2
    assert out["n_chunks"] == 4 and out["buffer_reuses"] == 2
    assert out["modeled_speedup"] >= 1.0
    # the non-vacuous engine signal (speedup >= 1 holds by algebra)
    assert out["overlap_ratio"] > 0.0


@needs_native
def test_pack_updates_into_reuse_is_clean():
    """Slot reuse can never alias stale bytes into a later decode: after
    re-packing a shorter payload over a longer one, the tail up to the
    previous occupant's length + guard is zeroed."""
    from ytpu.ops.decode_kernel import _PAD, pack_updates_into

    buf = np.zeros((4, 64), dtype=np.uint8)
    lens = np.zeros((4,), dtype=np.int32)
    pack_updates_into([b"\x01" * 40, b"\x02" * 8], buf, lens)
    assert lens.tolist() == [40, 8, 2, 2]  # short rows pad as EMPTY_UPDATE
    pack_updates_into([b"\x03" * 6], buf, lens)
    assert lens[0] == 6
    assert buf[0, :6].tolist() == [3] * 6
    assert not buf[0, 6 : 40 + _PAD].any(), "stale bytes survived reuse"
    with pytest.raises(ValueError, match="exceeds staging width"):
        pack_updates_into([b"\x04" * 60], buf, lens)


@needs_native
def test_capacity_exhausted_error_names_limit():
    """`max_capacity` BELOW the current capacity raises a proper
    capacity-exhausted error naming the limit — not grow_packed's
    misleading "cannot shrink" (PR-4 review). Driven through the
    driver's `ensure_room` directly: a chunk whose worst-case growth
    cannot fit must fail before the tile-corrupting ERR_CAPACITY."""
    from ytpu.models.batch_doc import init_state
    from ytpu.ops.decode_kernel import identity_rank
    from ytpu.ops.integrate_kernel import PackedReplayDriver, pack_state

    cols, meta = pack_state(init_state(N_DOCS, CAPACITY))
    drv = PackedReplayDriver(
        cols,
        meta,
        identity_rank(256),
        lane="xla",
        unit_refs=True,  # reuse this file's compiled compact family
        gc_ranges=True,
        max_capacity=CAPACITY // 4,  # below current capacity
    )
    with pytest.raises(RuntimeError, match=r"capacity-exhausted.*max_capacity"):
        drv.ensure_room(10 * CAPACITY)


@needs_native
def test_async_fused_interpret_or_skip():
    """The fused Pallas lane through the async pipeline — or a SKIP when
    this container's jax cannot interpret the kernel (memoized across
    files by tests/_fused_interpret)."""
    log, _, _ = _workload()
    prefix = log[: 2 * CHUNK]
    sync = _make(overlap=False)
    sync.run(prefix)
    asyn = _make(overlap=True, lane="fused", interpret=True)
    run_or_skip(lambda: asyn.run(prefix))
    for d in range(N_DOCS):
        assert asyn.get_string(d) == sync.get_string(d)
