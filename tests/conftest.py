"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

The tests exercise multi-chip sharding on a virtual CPU mesh
(xla_force_host_platform_device_count) — the real-TPU path is covered by
bench.py and the driver's compile checks.
"""

import os
import sys

# The tunneled-TPU plugin (axon) registers itself at interpreter start and
# can hang `import jax` indefinitely when the device tunnel is down — even
# under JAX_PLATFORMS=cpu. The tests are CPU-only by design, so restart the
# test process once with the registration env removed.
def _is_pytest_cli() -> bool:
    """Only a plain CLI invocation (`pytest …` / `python -m pytest …`) can
    be faithfully rebuilt as `python -m pytest argv[1:]`; programmatic
    pytest.main() callers and xdist worker bootstraps cannot."""
    a0 = os.path.basename(sys.argv[0])
    return a0 in ("pytest", "py.test") or sys.argv[0].endswith(
        os.path.join("pytest", "__main__.py")
    )


if (
    os.environ.get("PALLAS_AXON_POOL_IPS")
    and not os.environ.get("YTPU_TEST_REEXEC")
    and _is_pytest_cli()
):
    _env = dict(os.environ)
    _env.pop("PALLAS_AXON_POOL_IPS", None)
    _env["YTPU_TEST_REEXEC"] = "1"
    _env["JAX_PLATFORMS"] = "cpu"
    os.execve(
        sys.executable, [sys.executable, "-m", "pytest", *sys.argv[1:]], _env
    )

# Must be set before the JAX backend initializes.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# Some environments inject an accelerator platform ahead of the env var
# (e.g. a tunneled TPU plugin); pin to cpu explicitly for the test session.
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """The CPU backend segfaults inside backend_compile_and_load once the
    suite accumulates a few hundred compiled programs (deterministic at
    ~180 tests in). Dropping caches between modules keeps the compiler
    healthy at the cost of some recompilation."""
    yield
    jax.clear_caches()
