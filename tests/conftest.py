"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

The tests exercise multi-chip sharding on a virtual CPU mesh
(xla_force_host_platform_device_count) — the real-TPU path is covered by
bench.py and the driver's compile checks.
"""

import os
import sys

# The tunneled-TPU plugin (axon) is imported at interpreter start (via a
# site hook) in every Python process, and its *device init* can hang
# indefinitely when the tunnel is down.  Registration alone is harmless;
# the hang only happens if a backend for the axon platform is initialized
# (e.g. jax.devices() with JAX_PLATFORMS=axon).  The tests are CPU-only by
# design, so pin the platform to cpu in the environment BEFORE jax is
# imported — jax never initializes backends at import time, so the axon
# plugin is never touched.
#
# (An earlier version of this file re-exec'd the whole pytest process with
# the axon env removed.  That silently swallowed all pytest output: pytest's
# fd-level capture is active while conftest files load, so the exec'd child
# inherited fd 1/2 pointing at pytest's private temp files.  Do not bring
# the exec back.)
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# Belt and braces: even if something imported jax before us, pin cpu.
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest  # noqa: F401 — fixtures may be added below

# Round-4 root cause of the CPU-backend segfault (upstream repro): XLA:CPU
# executables are JIT-compiled into one LLVM memory arena per process;
# after many LARGE programs accumulate (each distinct decode/apply shape
# is one), the arena's allocator fails — "LLVM compilation error: Cannot
# allocate memory" (execution_engine.cc) — and the failure is mishandled
# into a SIGSEGV inside `backend_compile_and_load` (deterministically
# ~110 tests in; faulthandler stack captured in round 4; a 650-distinct-
# SMALL-program repro does NOT crash, so program SIZE is load-bearing).
#
# Round 5 retires the conftest-level `jax.clear_caches()` workaround
# (which doubled suite wall time and fixed nothing for real servers):
# the library now bounds its OWN live program set — the big jitted entry
# points register with `ytpu.utils.progbudget`, whose per-function
# eviction (`fn.clear_cache()` on the largest holders) keeps the LLVM
# arena bounded from inside the serving paths. No test fixture needed.


def pytest_configure(config):
    # the tier-1 gate runs `-m 'not slow'`; register the marker so slow
    # smoke tests (bench exporter guard) don't warn as unknown
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 gate (-m 'not slow')"
    )
