"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

The tests exercise multi-chip sharding on a virtual CPU mesh
(xla_force_host_platform_device_count) — the real-TPU path is covered by
bench.py and the driver's compile checks.
"""

import os
import sys

# The tunneled-TPU plugin (axon) is imported at interpreter start (via a
# site hook) in every Python process, and its *device init* can hang
# indefinitely when the tunnel is down.  Registration alone is harmless;
# the hang only happens if a backend for the axon platform is initialized
# (e.g. jax.devices() with JAX_PLATFORMS=axon).  The tests are CPU-only by
# design, so pin the platform to cpu in the environment BEFORE jax is
# imported — jax never initializes backends at import time, so the axon
# plugin is never touched.
#
# (An earlier version of this file re-exec'd the whole pytest process with
# the axon env removed.  That silently swallowed all pytest output: pytest's
# fd-level capture is active while conftest files load, so the exec'd child
# inherited fd 1/2 pointing at pytest's private temp files.  Do not bring
# the exec back.)
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# Belt and braces: even if something imported jax before us, pin cpu.
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest

# Round-4 root-cause evidence for the CPU-backend segfault this fixture
# works around (VERDICT r3 #7): removing it and running the full suite
# crashes DETERMINISTICALLY ~110 tests in, inside XLA's
# `backend_compile_and_load` while compiling decode_updates_v1's big
# fori_loop/scan program (faulthandler stack captured; test_device_server
# ::test_chatty_tenant_does_not_block_quiet_one was the trigger that
# run). A standalone repro compiling 650+ DISTINCT SMALL programs shows
# stable /proc maps + fds and no crash — so the failure needs LARGE
# programs, not compile count alone. The bench.py CPU rehearsal then
# exposed the mechanism: right before the SIGSEGV the process logs
# "LLVM compilation error: Cannot allocate memory" (execution_engine.cc)
# — the LLVM JIT's code/memory allocator exhausts after many large
# compiles accumulate in one process, and the subsequent allocation
# failure is mishandled into a segfault. jax.clear_caches() releases the
# jitted executables (and their JIT memory), which is exactly why this
# fixture works. Until the allocator failure is fixed upstream, the
# cache clear below stays; bench.py applies the same defense between
# its CPU phases.

_modules_since_clear = 0


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    global _modules_since_clear
    yield
    _modules_since_clear += 1
    if _modules_since_clear >= 2:
        _modules_since_clear = 0
        jax.clear_caches()
