"""Device-engine parity for move ranges (ContentMove).

Scenarios build update streams with host docs (YArray move_to /
move_range_to, reference moving.rs:149-227), then apply the same stream to
(a) a fresh host doc and (b) the batched device engine, and compare the
visible sequences. Covers: collapsed moves, range moves, concurrent moves
with priority reconciliation (both arrival orders), inserts into a moved
range (moved-flag inheritance + conflict recompute), and deletion of a move
item (range release / shadowed-move reintegration via the recompute pass).
"""

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    get_values,
    init_state,
)


def capture(doc: Doc):
    log = []
    doc.observe_update_v1(lambda payload, origin, txn: log.append(payload))
    return log


def device_replay(update_stream, capacity=128):
    enc = BatchEncoder(root_name="a")
    state = init_state(1, capacity)
    for payload in update_stream:
        u = Update.decode_v1(payload)
        batch = enc.build_batch([u])
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    return state, enc


def host_replay(update_stream) -> Doc:
    doc = Doc(client_id=0xDEAD)
    for payload in update_stream:
        doc.apply_update_v1(payload)
    return doc


def assert_parity(update_stream, capacity=128):
    host = host_replay(update_stream)
    state, enc = device_replay(update_stream, capacity=capacity)
    assert int(state.error[0]) == 0, f"device error flag {int(state.error[0])}"
    expect = host.get_array("a").to_json()
    got = get_values(state, 0, enc.payloads)
    assert got == expect, f"device {got!r} != host {expect!r}"
    assert host.store.pending is None
    return host, state, enc


def seeded_array(values, client_id=1):
    doc = Doc(client_id=client_id)
    log = capture(doc)
    arr = doc.get_array("a")
    with doc.transact() as txn:
        for v in values:
            arr.push_back(txn, v)
    return doc, arr, log


def test_collapsed_move_to():
    doc, arr, log = seeded_array([0, 1, 2, 3, 4])
    with doc.transact() as txn:
        arr.move_to(txn, 1, 4)  # [0, 2, 3, 1, 4]
    assert arr.to_json() == [0, 2, 3, 1, 4]
    assert_parity(log)


def test_move_range_backward():
    doc, arr, log = seeded_array(list(range(6)))
    with doc.transact() as txn:
        arr.move_range_to(txn, 3, 4, 1)  # [0, 3, 4, 1, 2, 5]
    assert arr.to_json() == [0, 3, 4, 1, 2, 5]
    assert_parity(log)


def test_move_then_edit_inside_range():
    """An insert landing inside a moved range inherits its owner."""
    doc, arr, log = seeded_array(list(range(5)))
    with doc.transact() as txn:
        arr.move_range_to(txn, 2, 3, 0)
    with doc.transact() as txn:
        arr.insert(txn, 2, ["x"])  # inside the moved destination
    state_json = arr.to_json()
    assert_parity(log)
    assert host_replay(log).get_array("a").to_json() == state_json


def test_concurrent_moves_both_orders():
    """Two peers move the same element; priority reconciliation must
    converge to the host-oracle result for both arrival orders."""
    a, arr_a, log_a = seeded_array([0, 1, 2, 3, 4], client_id=1)
    seed = list(log_a)
    b = Doc(client_id=2)
    log_b = capture(b)
    for p in seed:
        b.apply_update_v1(p)
    with a.transact() as txn:
        arr_a.move_to(txn, 1, 4)
    mv_a = log_a[-1]
    arr_b = b.get_array("a")
    with b.transact() as txn:
        arr_b.move_to(txn, 1, 3)
    mv_b = log_b[-1]
    for order in ([mv_a, mv_b], [mv_b, mv_a]):
        stream = seed + order
        host = host_replay(stream)
        state, enc = device_replay(stream)
        assert int(state.error[0]) == 0
        assert get_values(state, 0, enc.payloads) == host.get_array("a").to_json()


def test_concurrent_insert_into_moved_range():
    """Peer B inserts into a range peer A moved — the conflict case of the
    moved-flag inheritance (block.rs:677-702) lands in the recompute."""
    a, arr_a, log_a = seeded_array(list(range(5)), client_id=1)
    seed = list(log_a)
    b = Doc(client_id=2)
    log_b = capture(b)
    for p in seed:
        b.apply_update_v1(p)
    with a.transact() as txn:
        arr_a.move_range_to(txn, 1, 3, 5)
    mv_a = log_a[-1]
    arr_b = b.get_array("a")
    with b.transact() as txn:
        arr_b.insert(txn, 2, ["x"])  # between items 1 and 2 (pre-move coords)
    ins_b = log_b[-1]
    for order in ([mv_a, ins_b], [ins_b, mv_a]):
        stream = seed + order
        host = host_replay(stream)
        state, enc = device_replay(stream)
        assert int(state.error[0]) == 0
        assert get_values(state, 0, enc.payloads) == host.get_array("a").to_json()


def test_move_undo_releases_range():
    """Undoing a move deletes the ContentMove item: its range must release
    (and the array render in original order again)."""
    from ytpu.undo import UndoManager

    doc, arr, log = seeded_array(list(range(5)))
    mgr = UndoManager(doc, arr)
    with doc.transact() as txn:
        arr.move_to(txn, 0, 5)  # [1, 2, 3, 4, 0]
    assert arr.to_json() == [1, 2, 3, 4, 0]
    mgr.undo()
    assert arr.to_json() == [0, 1, 2, 3, 4]
    assert_parity(log)


def test_shadowed_move_reintegrates_after_undo():
    """A losing concurrent move must win again once the winner is undone
    (override reintegration, moving.rs:229-280)."""
    from ytpu.undo import UndoManager

    a, arr_a, log_a = seeded_array([0, 1, 2, 3, 4], client_id=1)
    seed = list(log_a)
    b = Doc(client_id=2)
    log_b = capture(b)
    for p in seed:
        b.apply_update_v1(p)
    arr_b = b.get_array("a")
    with b.transact() as txn:
        arr_b.move_to(txn, 1, 4)
    mv_b = log_b[-1]
    a.apply_update_v1(mv_b)
    mgr = UndoManager(a, arr_a)
    with a.transact() as txn:
        arr_a.move_to(txn, 1, 3)  # shadows b's move (adapted priority)
    mv_a = log_a[-1]
    mgr.undo()  # a's move dies; b's should own the element again
    undo_upd = log_a[-1]
    stream = seed + [mv_b, mv_a, undo_upd]
    host = host_replay(stream)
    state, enc = device_replay(stream)
    assert int(state.error[0]) == 0
    assert get_values(state, 0, enc.payloads) == host.get_array("a").to_json()


def test_collapsed_loser_is_tombstoned():
    """A claim that beats a *collapsed* move tombstones it on the spot
    (_delete_as_cleanup, moving.rs:190-196): after the winner is undone,
    the dead loser must NOT re-claim its element."""
    from ytpu.undo import UndoManager

    a, arr_a, log_a = seeded_array([0, 1, 2, 3, 4], client_id=1)
    seed = list(log_a)
    b = Doc(client_id=2)
    log_b = capture(b)
    for p in seed:
        b.apply_update_v1(p)
    with a.transact() as txn:
        arr_a.move_to(txn, 1, 4)  # collapsed loser (smaller client id)
    mv_a = log_a[-1]
    arr_b = b.get_array("a")
    mgr = UndoManager(b, arr_b)
    with b.transact() as txn:
        arr_b.move_to(txn, 1, 3)  # collapsed winner
    mv_b = log_b[-1]
    mgr.undo()  # winner dies; loser was tombstoned when beaten
    undo_b = log_b[-1]
    stream = seed + [mv_a, mv_b, undo_b]
    host = host_replay(stream)
    state, enc = device_replay(stream)
    assert int(state.error[0]) == 0
    got = get_values(state, 0, enc.payloads)
    expect = host.get_array("a").to_json()
    assert got == expect, f"device {got} != host {expect}"
    assert expect == [0, 1, 2, 3, 4]


def test_fuzz_random_moves_parity():
    import random

    rng = random.Random(1234)
    for round_ in range(6):
        doc, arr, log = seeded_array(list(range(8)))
        for _ in range(10):
            n = len(arr)
            op = rng.random()
            with doc.transact() as txn:
                if op < 0.45 and n >= 2:
                    s = rng.randrange(n)
                    t = rng.randrange(n + 1)
                    arr.move_to(txn, s, t)
                elif op < 0.6 and n >= 3:
                    s = rng.randrange(n - 1)
                    e = rng.randrange(s, n - 1)
                    t = rng.choice(
                        [x for x in range(n + 1) if x < s or x > e + 1]
                        or [n]
                    )
                    arr.move_range_to(txn, s, e, t)
                elif op < 0.8:
                    arr.insert(txn, rng.randrange(n + 1), [rng.randrange(100)])
                elif n > 1:
                    arr.remove_range(txn, rng.randrange(n), 1)
        host = host_replay(log)
        state, enc = device_replay(log, capacity=256)
        assert int(state.error[0]) == 0, f"round {round_}"
        got = get_values(state, 0, enc.payloads)
        expect = host.get_array("a").to_json()
        assert got == expect, f"round {round_}: {got} != {expect}"


def test_move_from_index_zero_branch_scoped_start():
    """A range starting at index 0 has a branch-scoped (no-id) start bound
    (IndexScope::Relative) — the device claim walk must read it as the
    sequence head, not as 'claims nothing'."""
    doc, arr, log = seeded_array(list(range(5)))
    with doc.transact() as txn:
        arr.move_range_to(txn, 0, 1, 4)
    assert_parity(log)


def test_move_to_index_zero():
    doc, arr, log = seeded_array(list(range(5)))
    with doc.transact() as txn:
        arr.move_to(txn, 3, 0)
    assert_parity(log)


def test_move_whole_sequence():
    """Both bounds branch-scoped: range [0, len) moved (degenerate but
    wire-legal)."""
    doc, arr, log = seeded_array(list(range(4)))
    with doc.transact() as txn:
        arr.move_range_to(txn, 0, 3, 4)
    assert_parity(log)


def test_concurrent_cross_moves_cycle_cleanup():
    """Two clients move overlapping ranges into each other — the losing
    move can close an ownership cycle; find_move_loop parity deletes it
    (moving.rs:113-141). Both arrival orders must converge with the host."""
    base_doc, _, base_log = seeded_array(list(range(6)), client_id=1)
    base = base_doc.encode_state_as_update_v1()

    d1 = Doc(client_id=2)
    d1.apply_update_v1(base)
    log1 = capture(d1)
    with d1.transact() as txn:
        d1.get_array("a").move_range_to(txn, 0, 2, 5)

    d2 = Doc(client_id=3)
    d2.apply_update_v1(base)
    log2 = capture(d2)
    with d2.transact() as txn:
        d2.get_array("a").move_range_to(txn, 3, 4, 1)

    assert_parity([base] + log1 + log2)
    assert_parity([base] + log2 + log1)


def test_nested_move_cycle_via_collapsed_moves():
    """Concurrent collapsed moves that shuttle each other's items."""
    base_doc, _, base_log = seeded_array(list(range(4)), client_id=1)
    base = base_doc.encode_state_as_update_v1()

    d1 = Doc(client_id=2)
    d1.apply_update_v1(base)
    log1 = capture(d1)
    with d1.transact() as txn:
        d1.get_array("a").move_to(txn, 0, 3)
        d1.get_array("a").move_to(txn, 2, 1)

    d2 = Doc(client_id=3)
    d2.apply_update_v1(base)
    log2 = capture(d2)
    with d2.transact() as txn:
        d2.get_array("a").move_to(txn, 3, 1)
        d2.get_array("a").move_to(txn, 1, 3)

    assert_parity([base] + log1 + log2)
    assert_parity([base] + log2 + log1)


def test_nested_branch_scoped_move():
    """A branch-scoped (index-0) move inside a NESTED array must claim from
    that branch's head, not the root sequence head."""
    from ytpu.models.batch_doc import get_tree
    from ytpu.types.shared import ArrayPrelim

    doc = Doc(client_id=1)
    log = capture(doc)
    root = doc.get_array("a")
    with doc.transact() as txn:
        root.push_back(txn, "keep")
        root.push_back(txn, ArrayPrelim([10, 11, 12, 13]))
    with doc.transact() as txn:
        nested = root.get(1)
        nested.move_range_to(txn, 0, 1, 4)  # branch-scoped start bound
    expect = doc.get_array("a").to_json()
    assert expect[1] == [12, 13, 10, 11]

    host = host_replay(log)
    assert host.get_array("a").to_json() == expect

    enc = BatchEncoder(root_name="a")
    state = init_state(1, 128)
    for payload in log:
        u = Update.decode_v1(payload)
        batch = enc.build_batch([u])
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    assert int(state.error[0]) == 0
    tree = get_tree(state, 0, enc.payloads, enc.keys)
    assert tree["seq"] == expect, f"device {tree['seq']!r} != host {expect!r}"
