"""Closed-loop fleet autopilot (ISSUE-16): deterministic journals,
flap damping, bounded-backoff recovery with a typed terminal state,
drain-then-kill maintenance that drops zero sessions, the runtime
admission setters, the autopilot fault sites, and the scored
autopilot-on vs autopilot-off chaos soak.

The decision logic runs against stub meshes (`FleetAutopilot` is
duck-typed over the `ReplicaMesh` actuator surface) so damping and
backoff are asserted tick by tick; the scored soak and the drain test
run the real 3-replica device-backed mesh at the suite-wide (4, 256)
family so nothing here compiles a new kernel shape.
"""

import pytest

from ytpu.serving import (
    AdmissionController,
    AutopilotConfig,
    FederatedSoakDriver,
    FleetAutopilot,
    QueueFull,
    RateLimited,
    RecoveryExhausted,
    Scenario,
    ScenarioConfig,
    SoakDriver,
)
from ytpu.serving import autopilot as autopilot_mod
from ytpu.serving.canary import CanaryProber
from ytpu.sync.device_server import DeviceSyncServer
from ytpu.sync.replica import ReplicaMesh
from ytpu.utils import metrics
from ytpu.utils.faults import faults


def _replica():
    # the suite-wide device family: every device-backed test shares
    # (n_docs=4, capacity=256) so jit caches are reused across files
    return DeviceSyncServer(n_docs=4, capacity=256)


# ------------------------------------------------------------- stub fleet


class _StubReplica:
    def __init__(self, alive=True):
        self.alive = alive


class _StubMesh:
    """The duck-typed actuator surface the policies call, recording
    every actuation instead of moving real state."""

    def __init__(self, rids=("r0", "r1")):
        self.replicas = {r: _StubReplica() for r in rids}
        self.owner = {}
        self.quarantined = set()
        self.decommissioned = set()
        self.migrations = []
        self.recover_calls = []
        self.recover_result = False

    def migrate_tenant(self, tenant, dst):
        self.migrations.append((tenant, dst))
        self.owner[tenant] = (dst, len(self.migrations))
        return len(self.migrations)

    def recover_tenant(self, tenant):
        self.recover_calls.append(tenant)
        if self.recover_result:
            self.quarantined.discard(tenant)
        return self.recover_result


# ------------------------------------------------- satellite: admission


def test_admission_runtime_setters_are_live_and_deterministic():
    """The ISSUE-16 runtime retuning surface under an injected clock:
    every setter takes effect on the NEXT admit, per-tenant overrides
    replace the globals, and every change bumps
    `admission.policy_changes`."""
    now = [0.0]
    adm = AdmissionController(
        max_queue=2, rate=2.0, burst=2.0, clock=lambda: now[0]
    )
    changes = metrics.counter("admission.policy_changes")
    base = changes.value

    # burst of 2 admits, third is rate-limited at t=0
    adm.admit("a", queue_depth=0)
    adm.admit("a", queue_depth=0)
    with pytest.raises(RateLimited):
        adm.admit("a", queue_depth=0)
    # retune the rate live: earned tokens are kept (zero here), so one
    # clock step at the NEW rate is enough where the old rate was not
    adm.set_rate(1000.0, burst=1000.0)
    now[0] += 0.01  # 10 tokens at 1000/s; 0.02 at the old rate
    adm.admit("a", queue_depth=0)

    # queue bound retune: depth 2 was at the old bound, passes the new
    with pytest.raises(QueueFull):
        adm.admit("a", queue_depth=2)
    adm.set_queue_bound(8)
    adm.admit("a", queue_depth=2)

    # per-tenant override replaces the global for that tenant only
    adm.set_tenant_queue_bound("hot", 1)
    with pytest.raises(QueueFull):
        adm.admit("hot", queue_depth=1)
    adm.admit("cold", queue_depth=1)
    adm.set_tenant_queue_bound("hot", None)  # clear back to global
    adm.admit("hot", queue_depth=1)

    snap = adm.policy_snapshot()
    assert snap["max_queue"] == 8
    assert snap["rate"] == 1000.0
    assert snap["tenant_queue_bounds"] == {}
    assert changes.value - base == 4  # one per setter call


# ----------------------------------------------------- policy: migration


def test_oscillating_load_is_damped_by_hysteresis_and_cooldown():
    """A load signal flapping across the watermarks every tick may not
    flap the tenant with it: the per-tenant cooldown bounds migrations
    to at most ceil(ticks / cooldown)."""
    mesh = _StubMesh()
    ticks = 40
    cooldown = 8
    state = {"n": 0}

    def snapshot():
        state["n"] += 1
        hot = state["n"] % 2 == 1  # above load_high, then below load_low
        load = 20.0 if hot else 0.0
        return {
            "tenants": {"zipf": {"owner": "r0", "depth": 0,
                                 "applied": 0, "load": load}},
            "replicas": {
                "r0": {"alive": True, "decommissioned": False,
                       "owned": ["zipf"], "load": load},
                "r1": {"alive": True, "decommissioned": False,
                       "owned": [], "load": 0.0},
            },
            "quarantined": [], "busy": 0, "admitted": 0,
            "busy_rate": 0.0, "pressure": 0,
        }

    ap = FleetAutopilot(
        mesh,
        config=AutopilotConfig(migrate_cooldown_ticks=cooldown),
        snapshot_fn=snapshot,
    )
    for _ in range(ticks):
        ap.tick()
    # damping bound: one migration per cooldown window, not per flap
    assert 1 <= len(mesh.migrations) <= -(-ticks // cooldown)
    migrated = [e for e in ap.journal if e["action"] == "migrate"]
    assert len(migrated) == len(mesh.migrations)
    # every migration journaled the inputs that justified it
    assert all(e["inputs"]["replica_load"] >= 16.0 for e in migrated)


# ------------------------------------------------------ policy: recovery


def test_recovery_backoff_gives_up_into_typed_terminal_state():
    """`recover_tenant` failures back off exponentially (bounded) and
    abandon the tenant into `RecoveryExhausted` after `max_recoveries`
    attempts — never an unbounded retry storm."""
    mesh = _StubMesh()
    mesh.quarantined = {"room"}
    ap = FleetAutopilot(
        mesh,
        config=AutopilotConfig(
            max_recoveries=3,
            recovery_backoff_base=1,
            recovery_backoff_mult=2,
            recovery_backoff_cap=4,
        ),
        snapshot_fn=lambda: {
            "quarantined": sorted(
                t for t in mesh.quarantined if t not in ap.terminal
            ),
            "tenants": {}, "replicas": {}, "busy": 0,
        },
    )
    for _ in range(12):
        ap.tick()
    # attempts at ticks 1, 3 (=1+min(2,4)), 7 (=3+min(4,4)), then stop
    assert mesh.recover_calls == ["room"] * 3
    term = ap.terminal["room"]
    assert isinstance(term, RecoveryExhausted)
    assert term.attempts == 3 and term.tick == 7
    assert autopilot_mod._RECOVERY_EXHAUSTED.value == 1.0
    backoffs = [e for e in ap.journal if e["action"] == "backoff"]
    assert [e["outcome"]["retry_tick"] for e in backoffs] == [3, 7]
    assert [e["action"] for e in ap.journal][-1] == "give_up"
    assert ap.report()["terminal"] == ["room"]


def test_recovery_success_clears_backoff_state():
    mesh = _StubMesh()
    mesh.quarantined = {"room"}
    mesh.recover_result = True
    ap = FleetAutopilot(
        mesh,
        snapshot_fn=lambda: {
            "quarantined": sorted(mesh.quarantined),
            "tenants": {}, "replicas": {}, "busy": 0,
        },
    )
    ap.tick()
    assert mesh.recover_calls == ["room"]
    assert not ap.terminal
    assert [e["action"] for e in ap.journal] == ["recover"]


# --------------------------------------------------- policy: maintenance


def test_drain_then_kill_drops_zero_sessions_and_keeps_availability():
    """The drained-kill satellite: `schedule_drain` migrates every
    owned tenant away, decommissions (sessions close with
    ``reason="drain"``), and the kill that follows drops ZERO sessions
    — no `reason="failover"` delta, no `canary.availability` dent."""
    mesh = ReplicaMesh([(f"r{i}", _replica()) for i in range(3)])
    mesh.ensure_tenant("a", owner="r2")
    mesh.ensure_tenant("b", owner="r2")
    for t in ("a", "b"):
        mesh.replicas["r2"].server.connect_frames(t)
    prober = CanaryProber(mesh)
    prober.tick()

    dropped = metrics.counter("net.sessions_dropped", labelnames=("reason",))
    failover_base = dropped.labels("failover").value
    drain_base = dropped.labels("drain").value

    ap = FleetAutopilot(mesh)
    ap.schedule_drain("r2", at_tick=1)
    entries = ap.tick()

    kill = [e for e in entries if e["action"] == "kill"]
    assert kill and kill[0]["outcome"]["sessions_dropped"] == 0
    assert not mesh.replicas["r2"].alive
    assert "r2" in mesh.decommissioned and ap.drained == {"r2"}
    # every real tenant left r2 BEFORE the kill
    assert mesh.owner["a"][0] != "r2" and mesh.owner["b"][0] != "r2"
    # the drop accounting: drain sessions closed, zero failover drops
    assert dropped.labels("failover").value == failover_base
    assert dropped.labels("drain").value > drain_base
    # the canary stops scoring the drained replica instead of charging
    # the planned kill as unavailability
    for _ in range(4):
        prober.tick()
    assert set(prober.availability().values()) == {1.0}


def test_drain_refuses_without_a_live_target():
    mesh = _StubMesh(rids=("r0",))
    mesh.owner = {"a": ("r0", 0)}
    ap = FleetAutopilot(mesh)
    with pytest.raises(ValueError):
        ap.drain_replica("r0")


# ------------------------------------------------------------ fault sites


def test_stall_fault_skips_ticks_and_journals_them():
    mesh = _StubMesh()
    calls = {"n": 0}

    def snapshot():
        calls["n"] += 1
        return {"tenants": {}, "replicas": {}, "quarantined": [],
                "busy": 0}

    ap = FleetAutopilot(mesh, snapshot_fn=snapshot)
    stalls_base = autopilot_mod._STALLS.value
    faults.clear()
    faults.arm("autopilot.stall", n=2)
    try:
        first = ap.tick()
        second = ap.tick()
        third = ap.tick()
    finally:
        faults.clear()
    assert [e["action"] for e in first + second] == ["stall", "stall"]
    assert calls["n"] == 1 and third == []  # only the third pass ran
    assert autopilot_mod._STALLS.value - stalls_base == 2
    # stalls are journaled but are NOT actions
    assert ap.report()["actions_by_policy"] == {}


def test_stall_and_misfire_under_chaos_soak_keep_byte_parity():
    """The two ISSUE-16 fault rows end to end: a stalled controller
    degrades the mesh gracefully (still converges, still oracle
    parity), and a misfiring one — a seeded wrong-but-legal migration —
    cannot move the byte-parity surface."""
    cfg = ScenarioConfig(
        n_tenants=3, n_sessions=4, events_per_session=8, seed=13
    )
    oracle = SoakDriver(_replica(), Scenario(cfg), flush_every=4).run()[
        "state_digest"
    ]
    mesh = ReplicaMesh([(f"r{i}", _replica()) for i in range(3)])
    ap = FleetAutopilot(mesh, seed=3)
    faults.clear()
    faults.arm("autopilot.stall", n=1)
    faults.arm("autopilot.misfire", n=1)
    try:
        rep = FederatedSoakDriver(
            mesh, Scenario(cfg), flush_every=4, sync_every=4,
            anti_entropy_every=12, autopilot=ap, autopilot_every=4,
        ).run()
    finally:
        faults.clear()
    assert rep["converged"]
    assert rep["state_digest"] == oracle
    actions = [(e["policy"], e["action"]) for e in ap.journal]
    assert ("fault", "stall") in actions
    assert ("misfire", "migrate") in actions


# ------------------------------------------------------- the scored soak


@pytest.mark.slow
def test_autopilot_on_beats_off_at_oracle_parity():
    """The tentpole acceptance surface: the SAME chaos soak (partition
    + heal, tight admission, r2 retired at 80%) scored with the
    autopilot off (abrupt failover kill) and on (adaptive admission +
    scripted drain).  ON must win on e2e p99_adj AND min canary
    availability, both legs hold oracle parity, and two same-seed ON
    runs produce byte-identical action journals.

    Slow-marked (four full soaks, ~60s on one core): the bench dry-run
    `autopilot` leg asserts this same surface inside the tier-1 window,
    so the gate still covers it — this is the standalone repro."""
    # the bench-leg shape: 192 events Busy-storm the off leg hard
    # enough (~50 refusals, each a >=50ms retry) that its e2e p99 sits
    # a full histogram bucket above the on leg — not edge-adjacent
    cfg = ScenarioConfig(
        n_tenants=3, n_sessions=8, events_per_session=24, seed=5
    )
    total = cfg.n_sessions * cfg.events_per_session
    oracle = SoakDriver(_replica(), Scenario(cfg), flush_every=4).run()[
        "state_digest"
    ]

    def leg(autopilot_on):
        faults.clear()
        faults.arm("replica.partition", n=1)
        faults.arm("replica.heal", n=1, after=1)
        mesh = ReplicaMesh([(f"r{i}", _replica()) for i in range(3)])
        adm = AdmissionController(max_queue=1)
        ap, kw = None, {}
        if autopilot_on:
            ap = FleetAutopilot(mesh, admission=adm, seed=7)
            ap.schedule_drain("r2", int(total * 0.8) // 4)
        else:
            kw = dict(failover_at=0.8, failover_replica="r2")
        try:
            rep = FederatedSoakDriver(
                mesh, Scenario(cfg), flush_every=4, sync_every=4,
                anti_entropy_every=12, canary_every=4, admission=adm,
                autopilot=ap, autopilot_every=4, **kw,
            ).run()
        finally:
            faults.clear()
        return rep, ap

    off, _ = leg(False)
    on, ap1 = leg(True)
    on2, ap2 = leg(True)

    for rep in (off, on, on2):
        assert rep["converged"]
        assert rep["state_digest"] == oracle
    # the controller WINS on both scored axes
    assert on["apply_e2e_p99_ms_adj"] < off["apply_e2e_p99_ms_adj"]
    assert (
        on["canary"]["availability_min"]
        > off["canary"]["availability_min"]
    )
    assert on["canary"]["availability_min"] == 1.0
    # the off leg's abrupt kill is the availability dent
    assert off["canary"]["availability"]["r2"] < 1.0
    # determinism: byte-identical journals across same-seed runs
    assert ap1.journal_bytes() == ap2.journal_bytes()
    assert ap1.journal_digest() == ap2.journal_digest()
    # the soak report carries the scored autopilot summary
    assert on["autopilot"]["actions"] == ap1.report()["actions"] > 0
    kills = [e for e in ap1.journal if e["action"] == "kill"]
    assert kills and kills[0]["outcome"]["sessions_dropped"] == 0


# ------------------------------------------------------------- the export


def test_snapshot_and_config_surface():
    with pytest.raises(TypeError):
        AutopilotConfig(no_such_knob=1)
    mesh = _StubMesh()
    ap = FleetAutopilot(
        mesh, seed=9,
        snapshot_fn=lambda: {"tenants": {}, "replicas": {},
                             "quarantined": [], "busy": 0},
    )
    ap.tick()
    snap = ap.snapshot()
    assert snap["tick"] == 1 and snap["seed"] == 9
    assert snap["journal"] == list(ap.journal)
    assert snap["journal_digest"] == ap.journal_digest()
