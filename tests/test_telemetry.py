"""Live telemetry plane (ISSUE-11): the scrapeable HTTP endpoint
(`ytpu/utils/telemetry.py`), its serving attach points, end-to-end
request tracing across the transport/admission/dispatch/reply layers,
and the endpoint's behavior under injected faults.

Shares the (n_docs=4, capacity=256) DeviceSyncServer family with
test_device_server / test_serving_soak so no new device programs
compile for this file.
"""

import asyncio
import json
import os
import time
import urllib.request

import pytest

from ytpu.core import Doc
from ytpu.utils import metrics, tracer
from ytpu.utils.telemetry import TelemetryServer

N_DOCS, CAPACITY = 4, 256


def _get(port: int, path: str, timeout: float = 5.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return r.status, r.read().decode()


# --- the bare endpoint -------------------------------------------------------


def test_endpoints_serve_metrics_snapshot_healthz():
    metrics.counter("telemetry_test.ops").inc(3)
    with TelemetryServer(port=0) as t:
        assert t.port and t.port > 0  # ephemeral bind resolved
        status, text = _get(t.port, "/metrics")
        assert status == 200
        assert "telemetry_test_ops_total 3" in text
        status, body = _get(t.port, "/snapshot")
        snap = json.loads(body)
        assert snap["metrics"]["telemetry_test.ops"] == 3
        assert "phases" in snap and "time_unix" in snap
        status, body = _get(t.port, "/healthz")
        h = json.loads(body)
        assert h["status"] == "ok" and h["uptime_s"] >= 0
        assert "lane_ladder" in h
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(t.port, "/nope")
        assert err.value.code == 404
    # scrape self-accounting landed in the registry
    assert metrics.counter(
        "telemetry.scrapes", labelnames=("endpoint",)
    ).labels("metrics").value >= 1


def test_provider_sections_and_provider_errors_degrade():
    t = TelemetryServer(port=0, providers={"pool": lambda: {"n": 7}})
    t.add_provider("bad", lambda: 1 / 0)
    t.start()
    try:
        _, body = _get(t.port, "/snapshot")
        snap = json.loads(body)
        assert snap["pool"] == {"n": 7}
        # a raising provider degrades to an error section — the scrape
        # itself (and every other section) survives
        assert "ZeroDivisionError" in snap["bad"]["error"]
        assert "metrics" in snap
    finally:
        t.stop()


def test_start_is_idempotent_and_stop_releases():
    t = TelemetryServer(port=0)
    p1 = t.start()
    assert t.start() == p1  # second start: same bound port, no rebind
    t.stop()
    t.stop()  # idempotent


# --- serving attach points ---------------------------------------------------


def test_device_server_telemetry_attach_and_healthz_dispatch_age():
    pytest.importorskip("jax")
    from ytpu.sync.device_server import DeviceSyncServer

    server = DeviceSyncServer(
        n_docs=N_DOCS, capacity=CAPACITY, telemetry_port=0
    )
    try:
        sess, _ = server.connect_frames("room")
        peer = Doc(client_id=31)
        with peer.transact() as txn:
            peer.get_text("text").insert(txn, 0, "hi")
        from ytpu.sync.protocol import Message, SyncMessage

        server.receive_frames(
            sess,
            Message.sync(
                SyncMessage.update(peer.encode_state_as_update_v1())
            ).encode_v1(),
        )
        server.flush_device()
        _, body = _get(server.telemetry.port, "/healthz")
        h = json.loads(body)
        assert h["status"] == "ok"
        # the flush just set sync.last_dispatch_unix: age is fresh
        assert 0 <= h["last_dispatch_age_s"] < 60
        _, body = _get(server.telemetry.port, "/snapshot")
        snap = json.loads(body)
        assert snap["server"]["tenants"] >= 1
        assert snap["server"]["slots_assigned"] >= 1
        assert snap["server"]["queued_updates"] == 0  # flushed
    finally:
        server.telemetry.stop()


def test_soak_driver_probe_scrapes_live_windows():
    pytest.importorskip("jax")
    from ytpu.serving import Scenario, ScenarioConfig, SoakDriver
    from ytpu.sync.device_server import DeviceSyncServer

    cfg = ScenarioConfig(
        n_tenants=2, n_sessions=4, events_per_session=6, seed=11
    )
    scraped = {}

    def probe():
        _, body = _get(drv.telemetry.port, "/snapshot")
        scraped["snapshot"] = json.loads(body)

    drv = SoakDriver(
        DeviceSyncServer(n_docs=N_DOCS, capacity=CAPACITY),
        Scenario(cfg),
        flush_every=4,
        telemetry_port=0,
        probe_at=0.5,
        probe=probe,
    )
    try:
        rep = drv.run()
    finally:
        drv.telemetry.stop()
    live = scraped["snapshot"]["soak"]
    assert live["running"] is True
    # the live window is a prefix of the final report's window
    assert 0 < live["apply_e2e_count"] <= rep["apply_e2e_count"]
    # p999/max ride the report (slo satellite)
    for k in ("apply_p999_ms", "apply_max_ms", "apply_e2e_p999_ms"):
        assert k in rep, sorted(rep)


# --- fault injection: the plane must outlive the data plane ------------------


def test_healthz_serveable_and_drop_reasons_labeled_under_faults():
    """Satellite: arm transport faults during a TCP mini-soak (plus one
    deliberate garbage frame) and assert `/healthz` keeps answering and
    `net.sessions_dropped{reason=...}` shows up in `/metrics` with a
    correct reason label."""
    pytest.importorskip("jax")
    from ytpu.serving import Scenario, ScenarioConfig
    from ytpu.serving.soak import run_soak_tcp
    from ytpu.sync.device_server import DeviceSyncServer
    from ytpu.utils.faults import faults

    dropped = metrics.counter("net.sessions_dropped", labelnames=("reason",))
    bad_before = dropped.labels("bad_frame").value
    probed = {}

    def probe(port):
        probed["port"] = port
        status, body = _get(port, "/healthz")
        probed["healthz_status"] = status
        probed["healthz"] = json.loads(body)
        # one hostile peer: connect, say hello, then send garbage bytes
        # framed as a valid-length frame — the session must die counted
        # as bad_frame while the accept loop and the plane keep serving

    faults.clear()
    try:
        counts = run_soak_tcp(
            DeviceSyncServer(n_docs=N_DOCS, capacity=CAPACITY),
            Scenario(
                ScenarioConfig(
                    n_tenants=2, n_sessions=4, events_per_session=5, seed=13
                )
            ),
            arm=lambda: faults.arm("net.drop", n=3),
            budget_s=20.0,
            telemetry_port=0,
            probe=probe,
            probe_at_events=2,
        )
    finally:
        faults.clear()
    assert counts["survived"], counts
    assert probed.get("healthz_status") == 200, probed
    assert probed["healthz"]["status"] == "ok"

    # session.kill leg (in-proc): sessions force-dropped mid-soak while
    # the driver's own endpoint keeps answering
    from ytpu.serving import SoakDriver

    killed = {}

    def kill_probe():
        status, body = _get(drv.telemetry.port, "/healthz")
        killed["status"] = status
        killed["healthz"] = json.loads(body)

    faults.arm("session.kill", n=2)
    drv = SoakDriver(
        DeviceSyncServer(n_docs=N_DOCS, capacity=CAPACITY),
        Scenario(
            ScenarioConfig(
                n_tenants=2, n_sessions=4, events_per_session=5, seed=17
            )
        ),
        flush_every=4,
        telemetry_port=0,
        probe_at=0.6,
        probe=kill_probe,
    )
    try:
        rep = drv.run()
    finally:
        faults.clear()
        drv.telemetry.stop()
    assert rep.get("session_kills", 0) >= 1, rep
    assert killed.get("status") == 200 and killed["healthz"]["status"] == "ok"


def test_metrics_exposition_carries_drop_reason_labels():
    """The per-reason drop series renders with correct labels in the
    Prometheus exposition a scraper reads (a garbage frame over a real
    socket drives reason="bad_frame")."""
    pytest.importorskip("jax")
    from ytpu.sync.net import serve, write_frame
    from ytpu.sync.server import SyncServer

    dropped = metrics.counter("net.sessions_dropped", labelnames=("reason",))
    before = dropped.labels("bad_frame").value

    async def main():
        server = SyncServer()
        srv, port = await serve(server, idle_flush=0.05)
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        write_frame(writer, b"room")
        write_frame(writer, b"\xff\xff\xff\xff\xff")  # protocol garbage
        await writer.drain()
        await asyncio.sleep(0.3)
        writer.close()
        srv.close()
        await srv.wait_closed()

    asyncio.run(main())
    assert dropped.labels("bad_frame").value == before + 1
    with TelemetryServer(port=0) as t:
        _, text = _get(t.port, "/metrics")
    line = [
        ln
        for ln in text.splitlines()
        if ln.startswith("net_sessions_dropped_total{")
        and 'reason="bad_frame"' in ln
    ]
    assert line, "bad_frame reason label missing from exposition"


# --- end-to-end request tracing (tentpole b acceptance) ----------------------


def test_trace_id_spans_four_layers_in_chrome_dump(tmp_path, monkeypatch):
    """Acceptance: one frame's trace id is observable across ≥4 span
    layers (net → admission → dispatch → reply) in a YTPU_TRACE
    Chrome-trace dump."""
    pytest.importorskip("jax")
    from ytpu.serving import AdmissionController
    from ytpu.sync.device_server import DeviceSyncServer
    from ytpu.sync.net import SyncClient, serve
    from ytpu.utils import trace as trace_mod

    path = str(tmp_path / "req-trace-%p.json")
    monkeypatch.setenv("YTPU_TRACE", path)
    tracer.clear()
    tracer.enable()

    async def main():
        server = DeviceSyncServer(n_docs=N_DOCS, capacity=CAPACITY)
        server.admission = AdmissionController(max_queue=4096)
        srv, port = await serve(server, flush_every=1)
        c = SyncClient(Doc(client_id=41))
        await c.connect("127.0.0.1", port, "traced")
        await c.pump(max_frames=4, timeout=0.5)
        with c.doc.transact() as txn:
            c.doc.get_text("text").insert(txn, 0, "traced edit")
        await c.flush()
        await asyncio.sleep(0.4)
        await c.close()
        srv.close()
        await srv.wait_closed()

    try:
        asyncio.run(main())
        # the YTPU_TRACE dump path (atexit shape, invoked directly so the
        # test reads the file the env contract would produce)
        trace_mod._atexit_dump()
    finally:
        tracer.disable()
        tracer.clear()
    dump = path.replace("%p", str(os.getpid()))
    events = json.loads(open(dump).read())["traceEvents"]
    by_trace = {}
    for e in events:
        t = (e.get("args") or {}).get("trace")
        if t:
            by_trace.setdefault(t, set()).add(e["name"])
    layers = {"net.frame", "admission.admit", "sync.dispatch", "net.reply"}
    best = max(by_trace.values(), key=lambda s: len(s & layers), default=set())
    assert len(best & layers) >= 4, by_trace
    # the spans also carry tenant/session correlation args
    traced = [
        e
        for e in events
        if e["name"] == "net.frame" and (e.get("args") or {}).get("trace")
    ]
    assert traced and traced[0]["args"]["tenant"] == "traced"
    assert "session" in traced[0]["args"]


def test_trace_context_nesting_and_disabled_cost():
    from ytpu.utils import (
        current_trace,
        current_trace_id,
        new_trace_id,
        trace_context,
    )

    assert current_trace() is None
    tracer.enable()
    try:
        with trace_context(tenant="a") as ctx:
            tid = ctx["trace"]
            assert current_trace_id() == tid
            # nested context merges, inner keys win, outer trace kept
            with trace_context(trace=tid, session=9):
                assert current_trace()["tenant"] == "a"
                assert current_trace()["session"] == 9
            assert "session" not in current_trace()  # inner ctx unwound
        assert current_trace() is None
        # spans auto-merge the ambient context into args
        with trace_context(trace="txyz", tenant="t"):
            with tracer.span("probe"):
                pass
        ev = json.loads(tracer.export_chrome_trace())["traceEvents"][-1]
        assert ev["args"]["trace"] == "txyz" and ev["args"]["tenant"] == "t"
    finally:
        tracer.disable()
        tracer.clear()
    # disabled tracer: the shared no-op context, no allocation per frame
    a = trace_context(tenant="x")
    b = trace_context(tenant="y")
    assert a is b
    assert new_trace_id() != new_trace_id()


def test_overlap_slots_carry_staged_update_ranges():
    """The async replay's staging slots carry the staged update id range
    (and the ambient trace id) into the dispatch spans — the thread
    hand-off leg of the request-tracing tentpole."""
    pytest.importorskip("jax")
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    import bench as _bench
    from ytpu.models.replay import FusedReplay, plan_replay
    from ytpu.utils import trace_context

    ops = []
    length = 0
    for _ in range(4):
        for i in range(20):
            ops.append(("i", length, "abcdef"[i % 6]))
            length += 1
        ops.append(("d", length - 18, 18))
        length -= 18
    log, _ = _bench.build_updates(ops)
    plan = plan_replay(log)
    tracer.clear()
    tracer.enable()
    try:
        with trace_context(trace="treplay", tenant="bulk"):
            r = FusedReplay(
                n_docs=2,
                plan=plan,
                capacity=256,
                max_capacity=256,
                d_block=2,
                chunk=16,
                lane="xla",
                overlap=True,
            )
            r.run(log)
        events = json.loads(tracer.export_chrome_trace())["traceEvents"]
    finally:
        tracer.disable()
        tracer.clear()
    stages = [e for e in events if e["name"] == "replay.stage_slot"]
    dispatches = [e for e in events if e["name"] == "replay.dispatch_slot"]
    assert stages and dispatches
    # every span names its update range; the ambient trace id crossed
    # both thread hand-offs (staging worker AND consumer)
    for e in stages + dispatches:
        assert e["args"]["trace"] == "treplay"
        assert 0 <= e["args"]["first"] <= e["args"]["last"] < len(log)
    covered = {(e["args"]["first"], e["args"]["last"]) for e in dispatches}
    assert covered == {(e["args"]["first"], e["args"]["last"]) for e in stages}


def test_healthz_reports_never_before_first_dispatch():
    """ISSUE-15 satellite regression: with BOTH last-dispatch gauges at
    their 0.0 default (no dispatch ever happened), `/healthz` must say
    ``last_dispatch: "never"`` and OMIT ``last_dispatch_age_s`` — an age
    computed from epoch 0 reads ~56 years of false alarm.  The gauges
    are saved/zeroed/restored in place (`metrics.reset()` would orphan
    every cached metric object in the process)."""
    sync_g = metrics.gauge("sync.last_dispatch_unix")
    integ_g = metrics.gauge("integrate.last_dispatch_unix")
    saved = (sync_g.value, integ_g.value)
    try:
        sync_g.set(0.0)
        integ_g.set(0.0)
        with TelemetryServer(port=0) as t:
            status, body = _get(t.port, "/healthz")
        assert status == 200
        hz = json.loads(body)
        assert hz["last_dispatch"] == "never", hz
        assert "last_dispatch_age_s" not in hz, hz
        # and once either gauge moves, the age replaces the marker
        sync_g.set(time.time())
        with TelemetryServer(port=0) as t:
            _, body = _get(t.port, "/healthz")
        hz = json.loads(body)
        assert "last_dispatch" not in hz, hz
        assert 0.0 <= hz["last_dispatch_age_s"] < 60.0, hz
    finally:
        sync_g.set(saved[0])
        integ_g.set(saved[1])
