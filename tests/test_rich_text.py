"""Rich-text parity: snapshot diff_range/YChange + TextEvent attribute deltas.

Reference behavior: /root/reference/yrs/src/types/text.rs — DiffIterator with
snapshot visibility (:534-634), YChange (:1190), event-delta state machine
(:1213-1305).
"""

from ytpu.core import Doc
from ytpu.types.events import Change
from ytpu.types.text import Diff, YChange


def test_diff_range_added_and_removed():
    # skip_gc keeps tombstoned content renderable (same caveat as the
    # reference's encode_state_from_snapshot, lib.rs:410-417)
    doc = Doc(client_id=1, skip_gc=True)
    txt = doc.get_text("t")
    with doc.transact() as txn:
        txt.insert(txn, 0, "hello world")
    lo = doc.snapshot()
    with doc.transact() as txn:
        txt.remove_range(txn, 0, 6)       # drop "hello "
        txt.insert(txn, 5, "!")           # "world!"
    hi = doc.snapshot()
    with doc.transact() as txn:
        runs = txt.diff_range(txn, hi, lo)
    assert [r.insert for r in runs] == ["hello ", "world", "!"]
    assert runs[0].ychange.kind == YChange.REMOVED
    assert runs[1].ychange is None
    assert runs[2].ychange.kind == YChange.ADDED


def test_diff_range_current_vs_lo():
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    with doc.transact() as txn:
        txt.insert(txn, 0, "abc")
    lo = doc.snapshot()
    with doc.transact() as txn:
        txt.insert(txn, 3, "def")
    with doc.transact() as txn:
        runs = txt.diff_range(txn, None, lo)
    assert runs == [
        Diff("abc"),
        Diff("def", None, YChange(YChange.ADDED, runs[1].ychange.id)),
    ]


def test_diff_range_no_snapshots_matches_diff():
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    with doc.transact() as txn:
        txt.insert(txn, 0, "plain ")
        txt.insert_with_attributes(txn, 6, "bold", {"bold": True})
    with doc.transact() as txn:
        runs = txt.diff_range(txn, None, None)
    assert runs == txt.diff()
    assert runs == [Diff("plain "), Diff("bold", {"bold": True})]


def test_diff_range_keeps_formats_of_hi_snapshot():
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    with doc.transact() as txn:
        txt.insert_with_attributes(txn, 0, "xy", {"em": 1})
    hi = doc.snapshot()
    with doc.transact() as txn:
        txt.insert(txn, 2, "z")
    with doc.transact() as txn:
        runs = txt.diff_range(txn, hi, None)
    assert runs == [Diff("xy", {"em": 1})]


def test_event_delta_format_retain_attributes():
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    with doc.transact() as txn:
        txt.insert(txn, 0, "hello world")
    deltas = []
    txt.observe(lambda txn, e: deltas.append(e.delta()))
    with doc.transact() as txn:
        txt.format(txn, 0, 5, {"bold": True})
    assert deltas == [[Change.retain(5, {"bold": True})]]


def test_event_delta_insert_with_attributes():
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    with doc.transact() as txn:
        txt.insert(txn, 0, "hello world")
    deltas = []
    txt.observe(lambda txn, e: deltas.append(e.delta()))
    with doc.transact() as txn:
        txt.insert_with_attributes(txn, 5, "XX", {"italic": True})
    assert deltas == [
        [Change.retain(5), Change.insert(list("XX"), {"italic": True})]
    ]


def test_event_delta_unformat():
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    with doc.transact() as txn:
        txt.insert_with_attributes(txn, 0, "abc", {"bold": True})
        txt.insert(txn, 3, "def")
    deltas = []
    txt.observe(lambda txn, e: deltas.append(e.delta()))
    with doc.transact() as txn:
        txt.format(txn, 0, 3, {"bold": None})
    assert deltas == [[Change.retain(3, {"bold": None})]]


def test_event_delta_plain_ops_unchanged():
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    with doc.transact() as txn:
        txt.insert(txn, 0, "abcdef")
    deltas = []
    txt.observe(lambda txn, e: deltas.append(e.delta()))
    with doc.transact() as txn:
        txt.remove_range(txn, 1, 2)
        txt.insert(txn, 1, "XY")
    assert len(deltas) == 1
    kinds = [c.kind for c in deltas[0]]
    assert kinds[0] == "retain" and set(kinds) <= {"retain", "insert", "delete"}


def test_event_delta_deleted_mark_keeps_pending_attr():
    """Deleting an unformat mark re-bolds the following run; the event delta
    must keep the pending attribute past a later old mark with equal value."""
    doc = Doc(client_id=1)
    txt = doc.get_text("t")
    with doc.transact() as txn:
        txt.insert(txn, 0, "abc")
        txt.format(txn, 0, 1, {"bold": True})  # F(T) 'a' F(None) 'bc'
        txt.format(txn, 2, 1, {"bold": True})  # ... 'b' F(T) 'c' F(None)
    deltas = []
    txt.observe(lambda txn, e: deltas.append(e.delta()))
    with doc.transact() as txn:
        # delete the F(bold, None) mark between "a" and "b"
        item = txt.branch.start
        while item is not None:
            from ytpu.core.content import ContentFormat

            if isinstance(item.content, ContentFormat) and item.content.value is None:
                txn.delete(item)
                break
            item = item.right
    assert deltas and deltas[0], "formatting change must produce a delta"
    assert deltas[0] == [
        Change.retain(1),
        Change.retain(2, {"bold": True}),
    ]


def test_diff_range_remote_concurrent():
    """Annotations survive a merge of concurrent edits."""
    a, b = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a.get_text("t"), b.get_text("t")
    with a.transact() as txn:
        ta.insert(txn, 0, "base")
    b.apply_update_v1(a.encode_state_as_update_v1())
    lo = a.snapshot()
    with b.transact() as txn:
        tb.insert(txn, 4, "+remote")
    a.apply_update_v1(b.encode_state_as_update_v1(a.state_vector()))
    with a.transact() as txn:
        runs = ta.diff_range(txn, None, lo)
    assert [r.insert for r in runs] == ["base", "+remote"]
    assert runs[0].ychange is None
    assert runs[1].ychange.kind == YChange.ADDED
