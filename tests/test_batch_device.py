"""Device-engine parity: apply_update_batch vs the host oracle.

Every scenario builds update streams with host docs, then applies the same
stream to (a) a fresh host doc and (b) the batched device engine, and
compares the visible text. This is the semantic-diff harness from
SURVEY.md §7 step 2/3.
"""

import random
import string

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    get_string,
    init_state,
    state_vectors,
)


def capture_updates(doc: Doc):
    log = []
    doc.observe_update_v1(lambda payload, origin, txn: log.append(payload))
    return log


def device_replay(update_stream, n_docs=1, capacity=256):
    """Apply a list of update payloads sequentially to every doc slot."""
    enc = BatchEncoder()
    state = init_state(n_docs, capacity)
    for payload in update_stream:
        u = Update.decode_v1(payload)
        batch = enc.build_batch([u] * n_docs)
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    return state, enc


def host_replay(update_stream) -> Doc:
    doc = Doc(client_id=0xDEAD)
    for payload in update_stream:
        doc.apply_update_v1(payload)
    return doc


def assert_parity(update_stream, root="t", capacity=256):
    host = host_replay(update_stream)
    state, enc = device_replay(update_stream, capacity=capacity)
    assert int(state.error[0]) == 0, f"device error flag {int(state.error[0])}"
    expect = host.get_text(root).get_string()
    got = get_string(state, 0, enc.payloads)
    assert got == expect, f"device {got!r} != host {expect!r}"
    # pending must be empty on the host for a fair comparison
    assert host.store.pending is None
    return host, state, enc


def test_single_doc_appends():
    d = Doc(client_id=1)
    log = capture_updates(d)
    t = d.get_text("t")
    for i in range(5):
        with d.transact() as txn:
            t.insert(txn, len(t), f"chunk{i} ")
    assert_parity(log)


def test_single_doc_random_inserts_deletes():
    rng = random.Random(3)
    d = Doc(client_id=1)
    log = capture_updates(d)
    t = d.get_text("t")
    for _ in range(40):
        with d.transact() as txn:
            n = len(t)
            if n > 4 and rng.random() < 0.3:
                pos = rng.randint(0, n - 3)
                t.remove_range(txn, pos, rng.randint(1, 3))
            else:
                pos = rng.randint(0, n)
                t.insert(txn, pos, rng.choice(["ab", "xyz", "q", "hello"]))
    assert_parity(log)


def test_two_peer_concurrent_conflicts():
    a, b = Doc(client_id=1), Doc(client_id=2)
    la, lb = capture_updates(a), capture_updates(b)
    ta, tb = a.get_text("t"), b.get_text("t")
    # concurrent inserts at the same (empty) position — pure YATA conflict
    with a.transact() as txn:
        ta.insert(txn, 0, "AAA")
    with b.transact() as txn:
        tb.insert(txn, 0, "BBB")
    # interleave the two independent streams both ways
    for stream in ([la[0], lb[0]], [lb[0], la[0]]):
        assert_parity(stream)


def test_multi_round_concurrency():
    rng = random.Random(11)
    peers = [Doc(client_id=i + 1) for i in range(3)]
    logs = [capture_updates(p) for p in peers]
    texts = [p.get_text("t") for p in peers]
    rounds = []
    for rnd in range(4):
        marks = [len(lg) for lg in logs]
        for p, t in zip(peers, texts):
            for _ in range(rng.randint(1, 3)):
                with p.transact() as txn:
                    n = len(t)
                    if n > 3 and rng.random() < 0.35:
                        pos = rng.randint(0, n - 2)
                        t.remove_range(txn, pos, rng.randint(1, 2))
                    else:
                        t.insert(
                            txn,
                            rng.randint(0, n),
                            "".join(rng.choice(string.ascii_lowercase) for _ in range(3)),
                        )
        # updates captured this round, one bucket per peer
        round_updates = [lg[m:] for lg, m in zip(logs, marks)]
        rounds.append(round_updates)
        # full exchange ends the round
        from ytpu.testing import exchange_updates

        exchange_updates(peers)

    # causal stream: roundwise, random peer interleaving (per-peer order kept)
    stream = []
    for round_updates in rounds:
        buckets = [list(b) for b in round_updates]
        while any(buckets):
            choices = [i for i, b in enumerate(buckets) if b]
            pick = rng.choice(choices)
            stream.append(buckets[pick].pop(0))
    host, state, enc = assert_parity(stream, capacity=1024)
    # all peers converged to the same string as the replays
    assert host.get_text("t").get_string() == texts[0].get_string()


@pytest.mark.parametrize("seed", range(5))
def test_fuzz_two_peer_parity(seed):
    rng = random.Random(seed + 1000)
    a, b = Doc(client_id=7), Doc(client_id=9)
    la, lb = capture_updates(a), capture_updates(b)
    ta, tb = a.get_text("t"), b.get_text("t")
    rounds = []
    from ytpu.testing import exchange_updates

    for rnd in range(3):
        ma, mb = len(la), len(lb)
        for doc, t in ((a, ta), (b, tb)):
            for _ in range(rng.randint(1, 4)):
                with doc.transact() as txn:
                    n = len(t)
                    roll = rng.random()
                    if n > 2 and roll < 0.3:
                        pos = rng.randint(0, n - 1)
                        t.remove_range(txn, pos, min(rng.randint(1, 4), n - pos))
                    else:
                        t.insert(txn, rng.randint(0, n), rng.choice(["zz", "q", "lmnop"]))
        rounds.append([la[ma:], lb[mb:]])
        exchange_updates([a, b])

    stream = []
    for buckets in rounds:
        buckets = [list(x) for x in buckets]
        while any(buckets):
            pick = rng.choice([i for i, x in enumerate(buckets) if x])
            stream.append(buckets[pick].pop(0))
    assert_parity(stream, capacity=1024)


def test_batched_docs_independent_streams():
    """Different docs in one batch receive different updates."""
    docs = [Doc(client_id=i + 1) for i in range(4)]
    logs = [capture_updates(d) for d in docs]
    for i, d in enumerate(docs):
        t = d.get_text("t")
        with d.transact() as txn:
            t.insert(txn, 0, f"doc-{i}-")
        with d.transact() as txn:
            t.insert(txn, len(t), "tail")
    enc = BatchEncoder()
    state = init_state(4, 64)
    for step in range(2):
        updates = [Update.decode_v1(logs[d][step]) for d in range(4)]
        batch = enc.build_batch(updates)
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    assert np.all(np.asarray(state.error) == 0)
    for i in range(4):
        assert get_string(state, i, enc.payloads) == f"doc-{i}-tail"


def test_state_vectors_device():
    d = Doc(client_id=5)
    log = capture_updates(d)
    t = d.get_text("t")
    with d.transact() as txn:
        t.insert(txn, 0, "hello")
    state, enc = device_replay(log)
    sv = np.asarray(state_vectors(state, max(1, len(enc.interner))))
    client_idx = enc.interner.to_idx[5]
    assert sv[0, client_idx] == 5


def test_multi_root_broadcast_stream_with_anchor_all():
    """A multi-root doc broadcast to every slot (the batched-replay shape):
    `ensure_root_anchor_all` seeds the non-primary root's anchor row in one
    vectorized dispatch, and every slot renders both roots."""
    from ytpu.models.batch_doc import (
        BatchEncoder,
        apply_update_stream,
        ensure_root_anchor_all,
        get_tree,
        init_state,
    )

    d = Doc(client_id=7)
    log = capture_updates(d)
    body = d.get_text("body")
    meta = d.get_map("meta")
    with d.transact() as txn:
        body.insert(txn, 0, "words")
    with d.transact() as txn:
        meta.insert(txn, "v", 2)
    with d.transact() as txn:
        body.insert(txn, 5, "!")

    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), 4, 4) for p in log]
    stream = BatchEncoder.stack_steps(steps)
    state = init_state(8, 64)
    state = ensure_root_anchor_all(state, enc.keys.intern("meta"))
    state = ensure_root_anchor_all(state, enc.keys.intern("meta"))  # idempotent
    state = apply_update_stream(state, stream, enc.interner.rank_table())
    assert np.all(np.asarray(state.error) == 0)
    for slot in (0, 7):
        tree = get_tree(state, slot, enc.payloads, enc.keys)
        assert tree["seq"] == list("words!")
        assert tree["roots"]["meta"]["map"] == {"v": 2}
    # exactly ONE anchor per doc despite the double seeding
    kinds = np.asarray(state.blocks.kind)
    n = np.asarray(state.n_blocks)
    from ytpu.core.content import BLOCK_ROOT_ANCHOR

    for slot in range(8):
        rows = kinds[slot, : n[slot]]
        assert int((rows == BLOCK_ROOT_ANCHOR).sum()) == 1
