"""TCP transport for the y-sync protocol (ytpu/sync/net.py).

Real sockets on localhost: handshake (SyncStep1 → SyncStep2 both ways),
live update broadcast between two clients of one tenant, tenant isolation,
and the device-backed server speaking the same transport.
"""

import asyncio

import numpy as np
import pytest

from ytpu.core import Doc
from ytpu.sync.net import SyncClient, serve
from ytpu.sync.server import SyncServer


def run(coro):
    return asyncio.run(coro)


def test_handshake_pulls_server_state():
    async def main():
        server = SyncServer()
        seed = server.doc("room")
        with seed.transact() as txn:
            seed.get_text("text").insert(txn, 0, "server state")
        srv, port = await serve(server)

        c = SyncClient(Doc(client_id=11))
        await c.connect("127.0.0.1", port, "room")
        # greeting: SyncStep1 (+ awareness); our step1 reply: SyncStep2
        await c.pump(max_frames=4, timeout=1.0)
        assert c.doc.get_text("text").get_string() == "server state"
        await c.close()
        srv.close()
        await srv.wait_closed()

    run(main())


def test_two_clients_converge_over_sockets():
    async def main():
        server = SyncServer()
        srv, port = await serve(server)

        a = SyncClient(Doc(client_id=21))
        b = SyncClient(Doc(client_id=22))
        await a.connect("127.0.0.1", port, "doc")
        await b.connect("127.0.0.1", port, "doc")
        await a.pump(max_frames=3, timeout=0.5)
        await b.pump(max_frames=3, timeout=0.5)

        with a.doc.transact() as txn:
            a.doc.get_text("text").insert(txn, 0, "alpha ")
        await a.flush()
        await asyncio.sleep(0.2)  # server processes before b pumps
        await b.pump(max_frames=2, timeout=1.0)

        with b.doc.transact() as txn:
            b.doc.get_text("text").insert(
                txn, len(b.doc.get_text("text").get_string()), "beta"
            )
        await b.flush()
        await asyncio.sleep(0.2)
        await a.pump(max_frames=2, timeout=1.0)

        sa = a.doc.get_text("text").get_string()
        sb = b.doc.get_text("text").get_string()
        assert sa == sb == "alpha beta", (sa, sb)
        assert server.doc("doc").get_text("text").get_string() == "alpha beta"
        await a.close()
        await b.close()
        srv.close()
        await srv.wait_closed()

    run(main())


def test_tenants_are_isolated():
    async def main():
        server = SyncServer()
        srv, port = await serve(server)
        a = SyncClient(Doc(client_id=31))
        b = SyncClient(Doc(client_id=32))
        await a.connect("127.0.0.1", port, "roomA")
        await b.connect("127.0.0.1", port, "roomB")
        await a.pump(max_frames=2, timeout=0.3)
        await b.pump(max_frames=2, timeout=0.3)
        with a.doc.transact() as txn:
            a.doc.get_text("text").insert(txn, 0, "private")
        await a.flush()
        await asyncio.sleep(0.3)  # let the server's handler process the frame
        await b.pump(max_frames=1, timeout=0.3)
        assert b.doc.get_text("text").get_string() == ""
        assert server.doc("roomA").get_text("text").get_string() == "private"
        assert server.doc("roomB").get_text("text").get_string() == ""
        await a.close()
        await b.close()
        srv.close()
        await srv.wait_closed()

    run(main())


def test_device_backed_server_over_sockets():
    from ytpu.sync.device_server import DeviceSyncServer

    async def main():
        server = DeviceSyncServer(n_docs=2, capacity=256)
        srv, port = await serve(server, flush_every=1)
        c = SyncClient(Doc(client_id=41))
        await c.connect("127.0.0.1", port, "room")
        await c.pump(max_frames=2, timeout=0.5)
        with c.doc.transact() as txn:
            c.doc.get_text("text").insert(txn, 0, "over the wire")
        await c.flush()
        # give the server a frame's worth of processing: ping via pump
        await asyncio.sleep(0.1)
        await c.pump(max_frames=1, timeout=0.3)
        server.flush_device()
        assert server.device_text("room") == "over the wire"
        assert int(np.asarray(server.ingestor.state.error).max()) == 0
        await c.close()
        srv.close()
        await srv.wait_closed()

    run(main())
