"""y-sync protocol, Awareness, and the multi-tenant server loop.

Model: reference sync/protocol.rs handlers + sync/awareness.rs tests.
"""

import pytest

from ytpu.core import Doc, StateVector
from ytpu.encoding.lib0 import Cursor
from ytpu.sync import (
    Awareness,
    AwarenessUpdate,
    Message,
    PermissionDenied,
    Protocol,
    SyncMessage,
    SyncServer,
    message_reader,
)
from ytpu.sync.awareness import AwarenessUpdateEntry


def test_message_roundtrip():
    sv = StateVector({1: 5, 9: 2})
    msgs = [
        Message.sync(SyncMessage.step1(sv)),
        Message.sync(SyncMessage.step2(b"\x01\x02\x03")),
        Message.sync(SyncMessage.update(b"\xff")),
        Message.auth(None),
        Message.auth("nope"),
        Message.awareness_query(),
        Message.awareness(AwarenessUpdate({7: AwarenessUpdateEntry(3, '{"x":1}')})),
    ]
    blob = b"".join(m.encode_v1() for m in msgs)
    out = list(message_reader(blob))
    assert out == msgs


def test_full_handshake_two_peers():
    a_doc, b_doc = Doc(client_id=1), Doc(client_id=2)
    ta, tb = a_doc.get_text("t"), b_doc.get_text("t")
    with a_doc.transact() as txn:
        ta.insert(txn, 0, "from-a")
    with b_doc.transact() as txn:
        tb.insert(txn, 0, "from-b")
    a, b = Awareness(a_doc), Awareness(b_doc)
    proto = Protocol()

    # a opens: sends step1 + awareness; b replies with step2 (+ applies)
    for msg in message_reader(proto.start(a)):
        reply = proto.handle_message(b, msg)
        if reply is not None:
            out = proto.handle_message(a, reply)
            assert out is None
    # now a has b's changes; reverse direction
    for msg in message_reader(proto.start(b)):
        reply = proto.handle_message(a, msg)
        if reply is not None:
            proto.handle_message(b, reply)
    assert ta.get_string() == tb.get_string()
    assert "from-a" in ta.get_string() and "from-b" in ta.get_string()


def test_auth_denied():
    doc = Doc(client_id=1)
    aw = Awareness(doc)
    proto = Protocol()
    with pytest.raises(PermissionDenied):
        proto.handle_message(aw, Message.auth("no access"))


def test_awareness_clock_precedence():
    doc = Doc(client_id=1)
    aw = Awareness(doc)
    aw.apply_update(AwarenessUpdate({5: AwarenessUpdateEntry(2, '{"v":1}')}))
    # stale clock must be ignored
    aw.apply_update(AwarenessUpdate({5: AwarenessUpdateEntry(1, '{"v":0}')}))
    assert aw.all_states()[5] == {"v": 1}
    # newer clock wins
    aw.apply_update(AwarenessUpdate({5: AwarenessUpdateEntry(3, '{"v":2}')}))
    assert aw.all_states()[5] == {"v": 2}
    # null removes
    aw.apply_update(AwarenessUpdate({5: AwarenessUpdateEntry(4, "null")}))
    assert 5 not in aw.all_states()


def test_awareness_local_state_resurrection():
    doc = Doc(client_id=42)
    aw = Awareness(doc)
    aw.set_local_state({"name": "me"})
    clock_before = aw.meta[42].clock
    # a remote peer claims we're gone — we must survive with a bumped clock
    aw.apply_update(AwarenessUpdate({42: AwarenessUpdateEntry(clock_before + 1, "null")}))
    assert aw.all_states()[42] == {"name": "me"}
    assert aw.meta[42].clock > clock_before


def test_awareness_timeout():
    t = [0.0]
    doc = Doc(client_id=1)
    aw = Awareness(doc, clock=lambda: t[0])
    aw.apply_update(AwarenessUpdate({9: AwarenessUpdateEntry(1, '{"p":1}')}))
    t[0] = 31_000.0
    removed = aw.remove_outdated()
    assert removed == [9]
    assert 9 not in aw.all_states()


def test_awareness_update_wire_roundtrip():
    u = AwarenessUpdate(
        {1: AwarenessUpdateEntry(4, '{"cursor":[1,2]}'), 2: AwarenessUpdateEntry(1, "null")}
    )
    assert AwarenessUpdate.decode_v1(u.encode_v1()) == u


def test_sync_server_two_clients():
    server = SyncServer()
    # client A connects and uploads its state
    ca = Doc(client_id=10)
    ta = ca.get_text("t")
    with ca.transact() as txn:
        ta.insert(txn, 0, "hello")
    sess_a, greeting_a = server.connect("room-1")
    proto = Protocol()
    aw_a = Awareness(ca)
    # client answers the greeting (step1 → step2 upload; awareness apply)
    for msg in message_reader(greeting_a):
        reply = proto.handle_message(aw_a, msg)
        if reply is not None:
            server.receive(sess_a, reply.encode_v1())
    # client also requests server state
    reply = server.receive(sess_a, proto.start(aw_a))
    for msg in message_reader(reply):
        proto.handle_message(aw_a, msg)
    assert server.doc("room-1").get_text("t").get_string() == "hello"

    # client B connects later and receives state via the greeting exchange
    cb = Doc(client_id=11)
    aw_b = Awareness(cb)
    sess_b, greeting_b = server.connect("room-1")
    for msg in message_reader(greeting_b):
        reply = proto.handle_message(aw_b, msg)
        if reply is not None:
            server.receive(sess_b, reply.encode_v1())
    reply = server.receive(sess_b, proto.start(aw_b))
    for msg in message_reader(reply):
        proto.handle_message(aw_b, msg)
    assert cb.get_text("t").get_string() == "hello"

    # live update from A broadcasts to B
    with ca.transact() as txn:
        ta.insert(txn, 5, " world")
    # ship A's latest update (captured via diff) to the server
    diff = ca.encode_state_as_update_v1(server.doc("room-1").state_vector())
    server.receive(sess_a, Message.sync(SyncMessage.update(diff)).encode_v1())
    frames = server.drain(sess_b)
    assert frames, "B should receive a broadcast"
    for frame in frames:
        for msg in message_reader(frame):
            proto.handle_message(aw_b, msg)
    assert cb.get_text("t").get_string() == "hello world"
    # A must not receive its own doc-update echo (awareness broadcasts are fine)
    for frame in server.drain(sess_a):
        for msg in message_reader(frame):
            assert msg.kind != 0, f"unexpected sync echo: {msg!r}"


def test_sync_server_tenant_isolation():
    server = SyncServer()
    s1, _ = server.connect("room-a")
    s2, _ = server.connect("room-b")
    c = Doc(client_id=5)
    t = c.get_text("t")
    with c.transact() as txn:
        t.insert(txn, 0, "secret")
    diff = c.encode_state_as_update_v1(StateVector())
    server.receive(s1, Message.sync(SyncMessage.update(diff)).encode_v1())
    assert server.doc("room-a").get_text("t").get_string() == "secret"
    assert server.doc("room-b").get_text("t").get_string() == ""
    assert server.drain(s2) == []
