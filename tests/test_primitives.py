"""StateVector / IdSet / DeleteSet semantics (model: reference
state_vector.rs + id_set.rs unit tests)."""

from ytpu.core import ID, DeleteSet, IdSet, StateVector


def test_state_vector_merge_and_contains():
    a = StateVector({1: 5, 2: 3})
    b = StateVector({1: 2, 3: 7})
    a.merge(b)
    assert a.get(1) == 5 and a.get(2) == 3 and a.get(3) == 7
    # contains means "can apply a block starting at this clock"
    assert a.contains(ID(1, 5))
    assert a.contains(ID(1, 0))
    assert not a.contains(ID(1, 6))
    assert a.contains(ID(99, 0))


def test_state_vector_wire_roundtrip():
    sv = StateVector({10: 100, 2: 7, 55: 1})
    data = sv.encode_v1()
    assert StateVector.decode_v1(data) == sv
    # zero-clock entries are dropped on the wire
    sv2 = StateVector({1: 0, 2: 5})
    assert StateVector.decode_v1(sv2.encode_v1()) == StateVector({2: 5})


def test_id_set_squash_and_contains():
    s = IdSet()
    s.insert(ID(1, 0), 3)
    s.insert(ID(1, 5), 2)
    s.insert(ID(1, 3), 2)  # bridges the hole
    s.squash()
    assert s.clients[1] == [(0, 7)]
    assert s.contains(ID(1, 6))
    assert not s.contains(ID(1, 7))


def test_id_set_invert():
    s = IdSet()
    s.insert(ID(1, 2), 3)  # [2..5)
    s.insert(ID(1, 8), 1)  # [8..9)
    inv = s.invert()
    assert inv.clients[1] == [(0, 2), (5, 8)]


def test_delete_set_wire_roundtrip():
    ds = DeleteSet()
    ds.insert(ID(7, 0), 4)
    ds.insert(ID(7, 10), 5)
    ds.insert(ID(3, 2), 1)
    data = ds.encode_v1()
    out = DeleteSet.decode_v1(data)
    assert out == ds


def test_delete_set_merge():
    a = DeleteSet()
    a.insert(ID(1, 0), 5)
    b = DeleteSet()
    b.insert(ID(1, 5), 5)
    b.insert(ID(2, 0), 1)
    a.merge(b)
    assert a.clients[1] == [(0, 10)]
    assert a.clients[2] == [(0, 1)]
