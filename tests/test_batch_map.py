"""Device map component vs the host oracle.

The batched engine resolves map (parent_sub) rows as per-key chains with
LWW tails (parity: block.rs:537-602 conflict scan + :637-659 map entry
maintenance, conflict rule lib.rs:427-430 "higher client id wins").
"""

import random

import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    get_map,
    init_state,
)


def device_map_from_docs(docs, capacity=64):
    """Encode each host doc's full state and integrate on device."""
    enc = BatchEncoder(root_name="m")
    updates = [Update.decode_v1(d.encode_state_as_update_v1()) for d in docs]
    batch = enc.build_batch(updates)
    state = init_state(len(docs), capacity)
    state = apply_update_batch(state, batch, enc.interner.rank_table())
    return state, enc


def host_map(doc):
    return doc.get_map("m").to_json()


def test_map_basic_set_and_overwrite():
    doc = Doc(client_id=1)
    m = doc.get_map("m")
    with doc.transact() as txn:
        m.insert(txn, "a", 1)
        m.insert(txn, "b", "two")
    with doc.transact() as txn:
        m.insert(txn, "a", 111)  # overwrite

    state, enc = device_map_from_docs([doc])
    assert int(state.error[0]) == 0
    assert get_map(state, 0, enc.payloads, enc.keys) == host_map(doc)
    assert host_map(doc) == {"a": 111, "b": "two"}


def test_map_remove():
    doc = Doc(client_id=1)
    m = doc.get_map("m")
    with doc.transact() as txn:
        m.insert(txn, "keep", 1)
        m.insert(txn, "drop", 2)
    with doc.transact() as txn:
        m.remove(txn, "drop")

    state, enc = device_map_from_docs([doc])
    assert int(state.error[0]) == 0
    assert get_map(state, 0, enc.payloads, enc.keys) == {"keep": 1}


def test_map_concurrent_lww_conflict():
    """Concurrent writes to one key: higher client id wins, both orders."""
    a = Doc(client_id=10)
    b = Doc(client_id=20)
    for d, v in ((a, "from-a"), (b, "from-b")):
        with d.transact() as txn:
            d.get_map("m").insert(txn, "k", v)
    ua, ub = a.encode_state_as_update_v1(), b.encode_state_as_update_v1()
    a.apply_update_v1(ub)
    b.apply_update_v1(ua)

    assert host_map(a) == host_map(b) == {"k": "from-b"}
    state, enc = device_map_from_docs([a, b])
    for d in range(2):
        assert int(state.error[d]) == 0
        assert get_map(state, d, enc.payloads, enc.keys) == {"k": "from-b"}


def test_map_mixed_with_sequence():
    """Map rows and sequence rows share the engine without interference
    (the XmlText shape: text content + attributes on one branch)."""
    doc = Doc(client_id=1)
    t = doc.get_text("m")
    with doc.transact() as txn:
        t.insert(txn, 0, "hello")

    doc2 = Doc(client_id=2)
    m2 = doc2.get_map("m")
    with doc2.transact() as txn:
        m2.insert(txn, "lang", "en")
    # merge the map-write into the text doc (separate clients, one branch
    # name — the engine keys rows by parent_sub, not branch type)
    doc.apply_update_v1(doc2.encode_state_as_update_v1())

    state, enc = device_map_from_docs([doc])
    assert int(state.error[0]) == 0
    from ytpu.models.batch_doc import get_string

    assert get_string(state, 0, enc.payloads) == "hello"
    assert get_map(state, 0, enc.payloads, enc.keys) == {"lang": "en"}


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_map_fuzz_parity(seed):
    """Random concurrent map edits across 3 clients; device == host."""
    rng = random.Random(seed)
    keys = ["k0", "k1", "k2", "k3"]
    docs = [Doc(client_id=100 + i) for i in range(3)]

    for step in range(12):
        d = rng.choice(docs)
        m = d.get_map("m")
        with d.transact() as txn:
            if rng.random() < 0.75:
                m.insert(txn, rng.choice(keys), rng.randrange(1000))
            else:
                m.remove(txn, rng.choice(keys))
        if rng.random() < 0.5:
            # partial sync: one random pairwise exchange
            x, y = rng.sample(docs, 2)
            y.apply_update_v1(x.encode_state_as_update_v1(y.state_vector()))

    # full convergence
    for x in docs:
        for y in docs:
            if x is not y:
                y.apply_update_v1(x.encode_state_as_update_v1(y.state_vector()))
    expected = host_map(docs[0])
    for d in docs[1:]:
        assert host_map(d) == expected

    state, enc = device_map_from_docs(docs, capacity=128)
    for i in range(3):
        assert int(state.error[i]) == 0, f"doc {i} error {int(state.error[i])}"
        assert get_map(state, i, enc.payloads, enc.keys) == expected


def test_map_binary_and_embed_values():
    doc = Doc(client_id=1)
    m = doc.get_map("m")
    with doc.transact() as txn:
        m.insert(txn, "bin", b"\x01\x02")
        m.insert(txn, "n", 7)

    state, enc = device_map_from_docs([doc])
    assert int(state.error[0]) == 0
    got = get_map(state, 0, enc.payloads, enc.keys)
    assert got["n"] == 7
    assert bytes(got["bin"]) == b"\x01\x02"


def test_map_device_encode_roundtrip():
    """Map rows stored on device re-encode onto the wire with parent_sub
    intact: device diff vs empty SV -> fresh host doc -> same map."""
    import numpy as np
    import jax

    from ytpu.models.batch_doc import encode_diff_batch, finish_encode_diff

    src = Doc(client_id=5)
    m = src.get_map("m")
    with src.transact() as txn:
        m.insert(txn, "a", 1)
        m.insert(txn, "b", "two")
    with src.transact() as txn:
        m.insert(txn, "a", 42)  # overwrite -> origin-bearing map row

    state, enc = device_map_from_docs([src])
    n_clients = max(1, len(enc.interner))
    remote = jax.numpy.zeros((1, n_clients), jax.numpy.int32)
    ship, offsets, _, deleted = map(
        np.asarray, encode_diff_batch(state, remote, n_clients)
    )
    payload = finish_encode_diff(state, 0, ship, offsets, deleted, enc)

    dst = Doc(client_id=6)
    dst.apply_update_v1(payload)
    assert dst.get_map("m").to_json() == {"a": 42, "b": "two"}


def test_map_loser_row_tombstoned_on_device():
    """A losing concurrent map write integrates dead-on-arrival (parity:
    block.rs:751-765), so device-encoded diffs ship its tombstone."""
    import numpy as np
    import jax

    from ytpu.models.batch_doc import encode_diff_batch, finish_encode_diff

    a = Doc(client_id=10)
    b = Doc(client_id=20)
    for d, v in ((a, "loser"), (b, "winner")):
        with d.transact() as txn:
            d.get_map("m").insert(txn, "k", v)
    ua, ub = a.encode_state_as_update_v1(), b.encode_state_as_update_v1()

    enc = BatchEncoder(root_name="m")
    state = init_state(1, 16)
    # winner arrives first; the loser then lands mid-chain (right != None)
    for payload in (ub, ua):
        batch = enc.build_batch([Update.decode_v1(payload)])
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    assert int(state.error[0]) == 0
    assert get_map(state, 0, enc.payloads, enc.keys) == {"k": "winner"}

    bl = jax.tree.map(lambda x: np.asarray(x[0]), state.blocks)
    n = int(state.n_blocks[0])
    loser_rows = [
        i for i in range(n)
        if enc.interner.from_idx[int(bl.client[i])] == 10
    ]
    assert loser_rows and all(bl.deleted[i] for i in loser_rows)

    # the tombstone ships on the wire: fresh host doc agrees it is deleted
    n_clients = len(enc.interner)
    remote = jax.numpy.zeros((1, n_clients), jax.numpy.int32)
    ship, offsets, _, deleted = map(
        np.asarray, encode_diff_batch(state, remote, n_clients)
    )
    payload = finish_encode_diff(state, 0, ship, offsets, deleted, enc)
    fresh = Doc(client_id=99)
    fresh.apply_update_v1(payload)
    assert fresh.get_map("m").to_json() == {"k": "winner"}
    assert fresh.state_vector().get(10) == 1  # loser block known + dead
