"""ywasm binding-surface parity: exercise every free function in
ytpu.compat (the Yjs-shaped API of ywasm/src/lib.rs:80-448).

These are the functions a Yjs/ywasm user reaches for by name; each test
drives the compat wrapper end to end (bytes in, bytes out) rather than the
underlying ytpu.core methods directly."""

import pytest

from ytpu import compat
from ytpu.core import Doc, Snapshot, StateVector, Update


def make_doc(cid=1, text="hello"):
    doc = Doc(client_id=cid)
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, text)
    return doc


def test_encode_state_vector_and_update_roundtrip():
    doc = make_doc()
    sv = compat.encode_state_vector(doc)
    assert StateVector.decode_v1(sv).get(1) == 5
    update = compat.encode_state_as_update(doc)
    replica = Doc(client_id=2)
    compat.apply_update(replica, update)
    assert replica.get_text("t").get_string() == "hello"
    # diff against the replica's vector is empty-ish (no new blocks)
    diff = compat.encode_state_as_update(doc, compat.encode_state_vector(replica))
    u = Update.decode_v1(diff)
    assert not any(u.blocks.values())


def test_v2_roundtrip():
    doc = make_doc(text="v2 payload")
    update = compat.encode_state_as_update_v2(doc)
    replica = Doc(client_id=3)
    compat.apply_update_v2(replica, update)
    assert replica.get_text("t").get_string() == "v2 payload"


def test_merge_and_diff_updates():
    doc = Doc(client_id=4)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "ab")
    with doc.transact() as txn:
        t.insert(txn, 2, "cd")
    merged = compat.merge_updates(*log)
    replica = Doc(client_id=5)
    compat.apply_update(replica, merged)
    assert replica.get_text("t").get_string() == "abcd"
    # state vector straight from the merged bytes
    sv = compat.encode_state_vector_from_update(merged)
    assert StateVector.decode_v1(sv).get(4) == 4
    # diff of merged vs "seen the first two chars"
    partial = StateVector({4: 2}).encode_v1()
    rest = compat.diff_updates(merged, partial)
    replica2 = Doc(client_id=6)
    compat.apply_update(replica2, log[0])
    compat.apply_update(replica2, rest)
    assert replica2.get_text("t").get_string() == "abcd"


def test_merge_preserves_origins_on_random_positions():
    """Regression: merging contiguous carriers must NOT rewrite origins.
    Splitting at offset 0 stamps origin = (client, clock-1), which only
    coincides with the true origin for append-only streams — random-position
    inserts exposed misintegration after merge."""
    import random

    rng = random.Random(99)
    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    t = doc.get_text("t")
    n = 0
    for _ in range(60):
        with doc.transact() as txn:
            if n > 12 and rng.random() < 0.35:
                k = rng.randint(1, 5)
                pos = rng.randint(0, n - k)
                t.remove_range(txn, pos, k)
                n -= k
            else:
                w = "".join(rng.choice("lorem ipsum") for _ in range(rng.randint(1, 6)))
                t.insert(txn, rng.randint(0, n), w)
                n += len(w)
    expect = t.get_string()
    merged = compat.merge_updates(*log)
    replica = Doc(client_id=2)
    compat.apply_update(replica, merged)
    assert replica.get_text("t").get_string() == expect
    # contiguous-carrier merges must not mutate their inputs (the offset-0
    # split both emptied the input item and rewrote the emitted origin)
    us = [Update.decode_v1(p) for p in log[:3]]
    before = us[1].encode_v1()
    Update.merge(us)
    assert us[1].encode_v1() == before


def test_merge_and_sv_v2():
    doc = Doc(client_id=7)
    log = []
    doc.observe_update_v2(lambda p, o, t: log.append(p))
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "xy")
    with doc.transact() as txn:
        t.insert(txn, 2, "z")
    merged = compat.merge_updates_v2(*log)
    replica = Doc(client_id=8)
    compat.apply_update_v2(replica, merged)
    assert replica.get_text("t").get_string() == "xyz"
    sv = compat.encode_state_vector_from_update_v2(merged)
    assert StateVector.decode_v1(sv).get(7) == 3
    partial = StateVector({7: 2}).encode_v1()
    rest = compat.diff_updates_v2(merged, partial)
    replica2 = Doc(client_id=9)
    compat.apply_update_v2(replica2, log[0])
    compat.apply_update_v2(replica2, rest)
    assert replica2.get_text("t").get_string() == "xyz"


def test_debug_dumps():
    doc = make_doc(text="dbg")
    v1 = compat.encode_state_as_update(doc)
    assert "dbg" in compat.debug_update_v1(v1)
    v2 = compat.encode_state_as_update_v2(doc)
    assert "dbg" in compat.debug_update_v2(v2)


def test_snapshot_helpers():
    doc = Doc(client_id=10, skip_gc=True)
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "abcdef")
    snap1 = compat.snapshot(doc)
    with doc.transact() as txn:
        t.remove_range(txn, 0, 3)
    snap2 = compat.snapshot(doc)
    assert not compat.equal_snapshots(snap1, snap2)
    # encode/decode both formats
    for enc_fn, dec_fn in [
        (compat.encode_snapshot_v1, compat.decode_snapshot_v1),
        (compat.encode_snapshot_v2, compat.decode_snapshot_v2),
    ]:
        data = enc_fn(snap1)
        back = dec_fn(data)
        assert compat.equal_snapshots(snap1, back)
    # fragmented-but-equal delete sets compare equal (squash normalization)
    from ytpu.core.id_set import DeleteSet

    frag = DeleteSet()
    frag.insert_range(10, 0, 2)
    frag.insert_range(10, 2, 3)
    whole = DeleteSet()
    whole.insert_range(10, 0, 3)
    assert compat.equal_snapshots(
        Snapshot(snap2.state_vector, frag), Snapshot(snap2.state_vector, whole)
    )
    # historical render from the pre-delete snapshot
    payload = compat.encode_state_from_snapshot_v1(doc, snap1)
    replica = Doc(client_id=11)
    compat.apply_update(replica, payload)
    assert replica.get_text("t").get_string() == "abcdef"
    payload2 = compat.encode_state_from_snapshot_v2(doc, snap1)
    replica2 = Doc(client_id=12)
    compat.apply_update_v2(replica2, payload2)
    assert replica2.get_text("t").get_string() == "abcdef"


def test_sticky_index_helpers():
    doc = make_doc(cid=13, text="sticky")
    t = doc.get_text("t")
    with doc.transact() as txn:
        sticky = compat.create_sticky_index_from_type(txn, t, 3)
    data = compat.encode_sticky_index(sticky)
    back = compat.decode_sticky_index(data)
    assert back == sticky
    # concurrent prepend shifts the absolute offset
    with doc.transact() as txn:
        t.insert(txn, 0, "++")
    with doc.transact() as txn:
        assert compat.create_offset_from_sticky_index(txn, back) == 5


def test_merge_partial_overlap_does_not_mutate_inputs():
    """Regression: the partial-overlap path split carriers of the *input*
    updates in place, so re-encoding an input after merge() dropped bytes."""
    from ytpu.core.update import Update

    doc = Doc(client_id=9)
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "abcde")
    full = doc.encode_state_as_update_v1()  # one block [9, 0..5)
    u_prefix = Update.decode_v1(full)
    # truncate manually: keep clocks [0..2) by splitting a decoded copy
    blocks = next(iter(u_prefix.blocks.values()))
    item = blocks[0]
    item.split(2)
    u_a = Update(blocks={9: type(blocks)([item])})
    u_full = Update.decode_v1(full)
    before = u_full.encode_v1()
    merged = Update.merge([u_a, u_full])
    assert u_full.encode_v1() == before  # inputs untouched
    replica = Doc(client_id=10)
    replica.apply_update_v1(merged.encode_v1())
    assert replica.get_text("t").get_string() == "abcde"


# --- YText.applyDelta (ywasm/src/text.rs:335 apply_delta; oracle scenarios
# ported from the reference's tests-wasm/y-text.tests.js) -----------------


def _delta(text):
    return [
        (d.insert, d.attributes) if d.attributes else (d.insert, None)
        for d in text.diff()
    ]


def test_apply_delta_multiline_format():
    doc = Doc(client_id=1)
    t = doc.get_text("test")
    with doc.transact() as txn:
        t.insert(txn, 0, "Test\nMulti-line\nFormatting")
    with doc.transact() as txn:
        t.apply_delta(
            txn,
            [
                {"retain": 4, "attributes": {"bold": True}},
                {"retain": 1},
                {"retain": 10, "attributes": {"bold": True}},
                {"retain": 1},
                {"retain": 10, "attributes": {"bold": True}},
            ],
        )
    assert _delta(t) == [
        ("Test", {"bold": True}),
        ("\n", None),
        ("Multi-line", {"bold": True}),
        ("\n", None),
        ("Formatting", {"bold": True}),
    ]


def test_apply_delta_does_not_merge_formatted_empty_lines():
    doc = Doc(client_id=1)
    t = doc.get_text("test")
    with doc.transact() as txn:
        t.apply_delta(
            txn,
            [
                {"insert": "Text"},
                {"insert": "\n", "attributes": {"title": True}},
                {"insert": "\nText"},
                {"insert": "\n", "attributes": {"title": True}},
            ],
        )
    assert _delta(t) == [
        ("Text", None),
        ("\n", {"title": True}),
        ("\nText", None),
        ("\n", {"title": True}),
    ]


def test_apply_delta_embed():
    doc = Doc(client_id=1)
    t = doc.get_text("test")
    with doc.transact() as txn:
        t.apply_delta(txn, [{"insert": {"linebreak": "s"}}])
    assert _delta(t) == [({"linebreak": "s"}, None)]


def test_apply_delta_insert_unsets_surrounding_format():
    """Quill semantics: an insert without attributes inside a bold region
    must NOT inherit the bold (reference: pos.unset_missing, block.rs:954)."""
    doc = Doc(client_id=1)
    t = doc.get_text("test")
    with doc.transact() as txn:
        t.insert_with_attributes(txn, 0, "bold", {"b": True})
    with doc.transact() as txn:
        t.apply_delta(txn, [{"retain": 2}, {"insert": "plain"}])
    assert _delta(t) == [
        ("bo", {"b": True}),
        ("plain", None),
        ("ld", {"b": True}),
    ]


def test_apply_delta_snapshot_sequence():
    doc = Doc(client_id=1, skip_gc=True)
    t = doc.get_text("test")
    with doc.transact() as txn:
        t.apply_delta(txn, [{"insert": "abcd"}])
    snap1 = doc.snapshot()
    with doc.transact() as txn:
        t.apply_delta(txn, [{"retain": 1}, {"insert": "x"}, {"delete": 1}])
    snap2 = doc.snapshot()
    with doc.transact() as txn:
        t.apply_delta(
            txn, [{"retain": 2}, {"delete": 1}, {"insert": "x"}, {"delete": 1}]
        )
    with doc.transact() as txn:
        assert [d.insert for d in t.diff_range(txn, snap1)] == ["abcd"]
    with doc.transact() as txn:
        assert [d.insert for d in t.diff_range(txn, snap2)] == ["axcd"]
    with doc.transact() as txn:
        runs = [
            (d.insert, d.ychange.kind if d.ychange else None)
            for d in t.diff_range(txn, snap2, snap1)
        ]
    assert runs == [("a", None), ("x", "added"), ("b", "removed"), ("cd", None)]


def test_apply_delta_converges_across_peers():
    a, b = Doc(client_id=1), Doc(client_id=2)
    ta = a.get_text("test")
    with a.transact() as txn:
        ta.apply_delta(txn, [{"insert": "shared "}, {"insert": "bold", "attributes": {"b": True}}])
    b.apply_update_v1(a.encode_state_as_update_v1(StateVector()))
    tb = b.get_text("test")
    with b.transact() as txn:
        tb.apply_delta(txn, [{"retain": 7}, {"delete": 4}, {"insert": "BOLD", "attributes": {"b": True}}])
    a.apply_update_v1(b.encode_state_as_update_v1(a.state_vector()))
    assert ta.get_string() == tb.get_string() == "shared BOLD"
    assert _delta(ta) == _delta(tb)


# --- Awareness.remove_states (ywasm/src/awareness.rs:134) ----------------


def test_awareness_remove_states():
    from ytpu.sync.awareness import Awareness

    aw = Awareness(Doc(client_id=7))
    aw.set_local_state({"x": 1})
    events = []
    aw.on_change(lambda a, e: events.append(e))
    aw.remove_states([7])
    assert aw.all_states() == {}
    assert events and events[-1].removed == [7]
    # clean_local_state really removes (it must bypass the remote-removal
    # resurrection guard)
    aw.set_local_state({"x": 2})
    aw.clean_local_state()
    assert aw.all_states() == {}
    # and the removal replicates: a peer applying our update drops us too
    peer = Awareness(Doc(client_id=9))
    peer.apply_update(aw.update_with_clients([7]))
    assert 7 not in peer.all_states()
