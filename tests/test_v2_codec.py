"""lib0 v2 columnar codec: Yjs byte-capture conformance + v1/v2 cross checks.

Fixtures are Yjs-generated v2 payloads from the reference compatibility
corpus (/root/reference/yrs/src/tests/compatibility_tests.rs — generating JS
documented there): map_set :184, array_insert :225, xml_fragment :284,
utf32_lib0_v2_decoding :321.
"""

import random
import string

import pytest

from ytpu.core import Doc, Update

MAP_V2 = bytes(
    [
        0, 0, 5, 177, 153, 227, 163, 3, 0, 0, 1, 40, 17, 12, 116, 101, 115, 116,
        107, 49, 116, 101, 115, 116, 107, 50, 4, 2, 4, 2, 1, 1, 0, 2, 65, 0, 1,
        2, 0, 119, 2, 118, 49, 119, 2, 118, 50, 0,
    ]
)
MAP_V1 = bytes(
    [
        1, 2, 241, 204, 241, 209, 1, 0, 40, 1, 4, 116, 101, 115, 116, 2, 107, 49,
        1, 119, 2, 118, 49, 40, 1, 4, 116, 101, 115, 116, 2, 107, 50, 1, 119, 2,
        118, 50, 0,
    ]
)

ARRAY_V2 = bytes(
    [
        0, 0, 5, 144, 233, 212, 232, 18, 0, 0, 1, 8, 6, 4, 116, 101, 115, 116,
        4, 1, 1, 0, 1, 2, 1, 1, 0, 119, 1, 97, 119, 1, 98, 0,
    ]
)

XML_V2 = bytes(
    [
        0, 1, 0, 6, 208, 198, 246, 169, 18, 0, 1, 0, 0, 3, 7, 0, 135, 25, 22,
        102, 114, 97, 103, 109, 101, 110, 116, 45, 110, 97, 109, 101, 110, 111,
        100, 101, 45, 110, 97, 109, 101, 13, 9, 1, 1, 2, 6, 3, 0, 1, 2, 0, 0,
    ]
)

UTF32_V2 = bytes(
    [
        0, 1, 0, 11, 144, 161, 211, 222, 18, 226, 133, 156, 142, 8, 25, 23, 1, 0,
        4, 6, 0, 14, 0, 16, 14, 1, 2, 14, 4, 2, 4, 2, 20, 4, 10, 8, 10, 8, 10, 1,
        56, 55, 40, 4, 39, 0, 4, 0, 161, 0, 0, 0, 167, 0, 4, 0, 167, 0, 4, 0,
        167, 0, 4, 0, 7, 0, 1, 0, 0, 0, 40, 3, 71, 0, 1, 0, 132, 0, 129, 0, 132,
        0, 129, 0, 132, 0, 129, 0, 132, 0, 129, 0, 132, 0, 129, 0, 132, 237, 1,
        208, 1, 110, 111, 116, 101, 46, 103, 117, 105, 100, 110, 111, 116, 101,
        71, 117, 105, 100, 110, 111, 116, 101, 46, 111, 119, 110, 101, 114, 111,
        119, 110, 101, 114, 110, 111, 116, 101, 46, 116, 121, 112, 101, 110, 111,
        116, 101, 84, 121, 112, 101, 110, 111, 116, 101, 46, 112, 114, 105, 118,
        97, 116, 101, 105, 115, 80, 114, 105, 118, 97, 116, 101, 110, 111, 116,
        101, 46, 99, 114, 101, 97, 116, 101, 84, 105, 109, 101, 99, 114, 101, 97,
        116, 101, 84, 105, 109, 101, 110, 111, 116, 101, 46, 116, 105, 116, 108,
        101, 116, 105, 116, 108, 101, 102, 102, 195, 188, 108, 108, 101, 110,
        102, 195, 188, 108, 104, 108, 101, 110, 102, 195, 188, 104, 108, 101,
        110, 112, 114, 111, 115, 101, 109, 105, 114, 114, 111, 114, 112, 105,
        110, 100, 101, 110, 116, 116, 97, 103, 78, 97, 109, 101, 108, 105, 110,
        101, 72, 101, 105, 103, 104, 116, 98, 95, 105, 100, 229, 156, 168, 227,
        129, 174, 233, 159, 169, 229, 155, 189, 240, 159, 135, 176, 240, 159,
        135, 183, 240, 159, 135, 168, 240, 159, 135, 179, 240, 159, 135, 175,
        240, 159, 135, 181, 9, 8, 10, 5, 9, 8, 12, 9, 15, 74, 0, 5, 1, 6, 7, 6,
        11, 1, 6, 7, 10, 4, 65, 0, 2, 68, 1, 7, 1, 5, 0, 3, 1, 0, 0, 4, 66, 2,
        3, 6, 10, 65, 4, 2, 65, 4, 66, 0, 10, 69, 1, 2, 5, 0, 119, 22, 66, 71,
        108, 122, 109, 85, 106, 50, 84, 82, 45, 108, 100, 106, 102, 113, 49, 90,
        112, 82, 49, 81, 125, 34, 125, 0, 121, 119, 13, 49, 54, 53, 50, 57, 51,
        51, 50, 50, 50, 56, 56, 50, 30, 0, 125, 0, 119, 3, 100, 105, 118, 119,
        0, 119, 11, 74, 88, 98, 65, 83, 97, 45, 97, 57, 50, 106, 1, 226, 130,
        142, 135, 4, 8, 0, 19, 8, 1, 5, 1, 1, 1, 1, 9, 2, 4, 4, 4, 4, 4,
    ]
)


def test_map_v2_decode_matches_v1():
    u1 = Update.decode_v1(MAP_V1)
    u2 = Update.decode_v2(MAP_V2)
    assert set(u1.blocks.keys()) == set(u2.blocks.keys())
    for client in u1.blocks:
        b1 = list(u1.blocks[client])
        b2 = list(u2.blocks[client])
        assert len(b1) == len(b2)
        for x, y in zip(b1, b2):
            assert x.id == y.id and x.len == y.len
            assert x.parent == y.parent and x.parent_sub == y.parent_sub
            assert type(x.content) is type(y.content)


def test_map_v2_apply():
    doc = Doc(client_id=1)
    doc.apply_update_v2(MAP_V2)
    assert doc.get_map("test").to_json() == {"k1": "v1", "k2": "v2"}


def test_map_v2_reencode_byte_exact():
    u = Update.decode_v2(MAP_V2)
    assert u.encode_v2() == MAP_V2


def test_array_v2_apply_and_reencode():
    doc = Doc(client_id=1)
    doc.apply_update_v2(ARRAY_V2)
    assert doc.get_array("test").to_list() == ["a", "b"]
    assert Update.decode_v2(ARRAY_V2).encode_v2() == ARRAY_V2


def test_xml_v2_apply_and_reencode():
    doc = Doc(client_id=1)
    doc.apply_update_v2(XML_V2)
    frag = doc.get_xml_fragment("fragment-name")
    assert frag.get_string() == "<node-name></node-name>"
    assert Update.decode_v2(XML_V2).encode_v2() == XML_V2


def test_utf32_v2_prosemirror_capture():
    """Real-world prosemirror v2 capture with astral chars (flag emoji)."""
    doc = Doc(client_id=1)
    frag = doc.get_xml_fragment("prosemirror")
    doc.apply_update_v2(UTF32_V2)
    el = frag.get(0)
    attrs = dict(el.attributes())
    assert attrs == {
        "b_id": "JXbASa-a92j",
        "indent": "0",
        "tagName": "div",
        "lineHeight": "",
    }
    txt = el.get(0)
    assert txt.get_string() == "在の韩国🇰🇷🇨🇳🇯🇵"


def test_v1_v2_cross_roundtrip_random_docs():
    rng = random.Random(42)
    for trial in range(5):
        doc = Doc(client_id=trial + 1)
        t = doc.get_text("t")
        m = doc.get_map("m")
        arr = doc.get_array("a")
        with doc.transact() as txn:
            for _ in range(rng.randint(3, 10)):
                word = "".join(rng.choice(string.ascii_lowercase) for _ in range(4))
                t.insert(txn, rng.randint(0, len(t)), word)
                m.insert(txn, rng.choice("abc"), rng.randint(0, 99))
                arr.push_back(txn, word)
        with doc.transact() as txn:
            t.remove_range(txn, 0, 2)
        # encode v2 → decode v2 → fresh doc must equal v1 path
        v2 = doc.encode_state_as_update_v2()
        v1 = doc.encode_state_as_update_v1()
        d_v2, d_v1 = Doc(client_id=100), Doc(client_id=101)
        d_v2.apply_update_v2(v2)
        d_v1.apply_update_v1(v1)
        assert d_v2.to_json() == d_v1.to_json() == doc.to_json()
        # v2 is the columnar format: it should not be larger than v1 for
        # repetitive block runs (sanity, not a strict guarantee)
        assert isinstance(v2, bytes) and len(v2) > 0


def test_v2_update_event_payload():
    doc = Doc(client_id=1)
    log = []
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "v2 event")
        payload = txn.encode_update_v2()
    d2 = Doc(client_id=2)
    d2.apply_update_v2(payload)
    assert d2.get_text("t").get_string() == "v2 event"


def test_nested_maps_arrays_v2_roundtrip():
    """Port of the reference's negative_zero_decoding_v2 regression
    (compatibility_tests.rs:394-425): nested map/array prelims through a
    full v2 state encode must re-apply to an identical tree (the original
    bug was IntDiffOptRle emitting a negative-zero run)."""
    from ytpu.types.shared import ArrayPrelim, MapPrelim

    doc = Doc(client_id=1)
    root = doc.get_map("root")
    with doc.transact() as txn:
        root.insert(txn, "sequence", MapPrelim({}))
    seq = root.get("sequence")
    with doc.transact() as txn:
        seq.insert(txn, "id", "V9Uk9pxUKZIrW6cOkC0Rg")
        seq.insert(txn, "cuts", ArrayPrelim([]))
        seq.insert(txn, "name", "new sequence")
        root.insert(txn, "__version__", 1)
        root.insert(txn, "face_expressions", ArrayPrelim([]))
        root.insert(txn, "characters", ArrayPrelim([]))
    expected = root.to_json()

    buffer = doc.encode_state_as_update_v2()
    doc2 = Doc(client_id=2)
    doc2.apply_update_v2(buffer)
    assert doc2.get_map("root").to_json() == expected
