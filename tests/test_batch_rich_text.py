"""Device-engine parity for formatted text: `get_diff` runs vs Text.diff().

Formatting marks (ContentFormat), attributed inserts, format toggles and
removals, embeds, and concurrent formatting from two clients must render
identically from device block columns and from the host oracle
(reference types/text.rs:534- DiffIterator)."""

import numpy as np

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    get_diff,
    init_state,
)


def capture(doc):
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    return log


def device_state(log, capacity=256):
    enc = BatchEncoder(root_name="t")
    state = init_state(1, capacity)
    for payload in log:
        u = Update.decode_v1(payload)
        batch = enc.build_batch([u])
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    assert int(state.error[0]) == 0
    return state, enc


def assert_diff_parity(log):
    host = Doc(client_id=0xBEEF)
    for p in log:
        host.apply_update_v1(p)
    expect = host.get_text("t").diff()
    state, enc = device_state(log)
    got = get_diff(state, 0, enc.payloads)
    assert got == expect, f"device {got!r} != host {expect!r}"
    return expect


def test_attributed_insert_runs():
    doc = Doc(client_id=1)
    log = capture(doc)
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "plain ")
        t.insert_with_attributes(txn, 6, "bold", {"b": True})
        t.insert(txn, 10, " tail")
    runs = assert_diff_parity(log)
    assert any(r.attributes == {"b": True} for r in runs)


def test_format_range_and_unformat():
    doc = Doc(client_id=2)
    log = capture(doc)
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "abcdefgh")
    with doc.transact() as txn:
        t.format(txn, 2, 4, {"i": True})
    assert_diff_parity(log)
    with doc.transact() as txn:
        t.format(txn, 2, 4, {"i": None})  # remove the mark
    assert_diff_parity(log)


def test_overlapping_formats():
    doc = Doc(client_id=3)
    log = capture(doc)
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "0123456789")
    with doc.transact() as txn:
        t.format(txn, 0, 6, {"b": True})
    with doc.transact() as txn:
        t.format(txn, 3, 6, {"i": 1})
    runs = assert_diff_parity(log)
    assert any(r.attributes == {"b": True, "i": 1} for r in runs)


def test_concurrent_formatting_two_clients():
    d1 = Doc(client_id=4)
    log1 = capture(d1)
    with d1.transact() as txn:
        d1.get_text("t").insert(txn, 0, "shared text")
    base = d1.encode_state_as_update_v1()

    d2 = Doc(client_id=5)
    d2.apply_update_v1(base)
    log2 = capture(d2)
    with d2.transact() as txn:
        d2.get_text("t").format(txn, 0, 6, {"u": True})
    with d1.transact() as txn:
        d1.get_text("t").format(txn, 4, 7, {"b": True})

    full = log1 + log2
    assert_diff_parity(full)
    assert_diff_parity(log1[:1] + log2 + log1[1:])


def test_embed_run():
    doc = Doc(client_id=6)
    log = capture(doc)
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert(txn, 0, "pre")
        t.insert_embed(txn, 3, {"img": "x.png"})
        t.insert(txn, 4, "post")
    runs = assert_diff_parity(log)
    assert any(r.insert == {"img": "x.png"} for r in runs)


def test_deleted_formatted_text():
    doc = Doc(client_id=7)
    log = capture(doc)
    t = doc.get_text("t")
    with doc.transact() as txn:
        t.insert_with_attributes(txn, 0, "deleteme", {"b": True})
        t.insert(txn, 8, " keep")
    with doc.transact() as txn:
        t.remove_range(txn, 0, 8)
    assert_diff_parity(log)
