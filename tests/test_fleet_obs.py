"""Fleet observability plane (ISSUE-15): the wire trace-context
extension's codec + backward compatibility, cross-replica trace
propagation through the in-proc mesh, the merged `/fleet` exposition
under concurrent live scrapes, canary probing semantics, and the
`--compare-baseline` verdict embedding.

Compatibility is the load-bearing surface here: trace frames are a
PROTOCOL_VERSION 2 extension, so an old (version-1) peer must (a) never
emit them and (b) silently ignore ones it receives — a mixed-version
mesh converges with tracing on, losing only the old replica's spans.
"""

import json
import threading
import urllib.request

from ytpu.serving import (
    CANARY_PREFIX,
    FederatedSoakDriver,
    Scenario,
    ScenarioConfig,
    SoakDriver,
    server_state_digest,
)
from ytpu.sync.protocol import (
    MSG_TRACE,
    PROTOCOL_VERSION,
    TRACE_WIRE_VERSION,
    Message,
    Protocol,
    SyncMessage,
    decode_trace,
    message_reader,
    trace_message,
)
from ytpu.sync.replica import ReplicaMesh
from ytpu.sync.server import SyncServer
from ytpu.utils import metrics
from ytpu.utils.telemetry import TelemetryServer
from ytpu.utils.trace import trace_context, tracer

CFG = ScenarioConfig(n_tenants=2, n_sessions=4, events_per_session=6, seed=29)


def _get(port: int, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return r.status, r.read().decode()


# --------------------------------------------------------------- wire codec


def test_trace_message_round_trips():
    frame = trace_message("t1234-ab", "r0").encode_v1()
    assert frame[0] == MSG_TRACE
    msgs = list(message_reader(frame))
    assert len(msgs) == 1 and msgs[0].kind == MSG_TRACE
    ver, trace, origin = decode_trace(msgs[0].body)
    assert (ver, trace, origin) == (1, "t1234-ab", "r0")
    # origin is optional on the wire (client-side emission has none)
    msg = next(iter(message_reader(trace_message("tX").encode_v1())))
    _, trace2, origin2 = decode_trace(msg.body)
    assert (trace2, origin2) == ("tX", "")


def test_protocol_version_gates_emission_not_tolerance():
    """Version-1 servers never EMIT trace frames; EVERY version ignores
    a received one (forward tolerance is unconditional — an old binary
    meeting a new peer must not drop the session as a bad frame)."""
    assert PROTOCOL_VERSION >= TRACE_WIRE_VERSION
    for version in (1, PROTOCOL_VERSION):
        server = SyncServer(protocol=Protocol(version=version))
        sess, _greet = server.connect_frames("t0")
        # a bare trace frame: no reply, no error, session stays alive
        replies = server.receive_frames(
            sess, trace_message("tZ", "rX").encode_v1()
        )
        assert replies == []
        assert not sess.dead
        # and the session still serves real traffic afterwards
        sv_frame = Message.sync(
            SyncMessage.step1(server.tenant_state_vector("t0"))
        ).encode_v1()
        server.receive_frames(sess, sv_frame)
        assert not sess.dead


def test_old_version_server_emits_no_trace_frames():
    """The broadcast path of a version-1 server must stay byte-clean of
    MSG_TRACE even while the tracer runs with an ambient context."""
    old = SyncServer(protocol=Protocol(version=1))
    new = SyncServer()
    import ytpu.core as _core

    doc = _core.Doc(client_id=77)
    captured = []
    unsub = doc.observe_update_v1(lambda p, o, t: captured.append(p))
    txt = doc.get_text("text")
    with doc.transact() as txn:
        txt.insert(txn, 0, "hello")
    unsub()
    update = Message.sync(SyncMessage.update(captured[0])).encode_v1()
    tracer.enabled = True
    try:
        for server, expect_trace in ((old, False), (new, True)):
            writer, _ = server.connect_frames("t0")
            watcher, _ = server.connect_frames("t0")
            server.drain(watcher)
            with trace_context(tenant="t0", replica="rme"):
                server.receive_frames(writer, update)
            frames = server.drain(watcher)
            kinds = {f[0] for f in frames if f}
            assert (MSG_TRACE in kinds) == expect_trace, (
                server.protocol.version, kinds,
            )
    finally:
        tracer.enabled = False


def test_mixed_version_mesh_converges_with_tracing_on():
    """A 3-replica mesh whose MIDDLE replica speaks protocol version 1
    must converge to the clean oracle digest with the tracer live: new
    replicas' trace frames cross the old one unharmed (swallowed), and
    the old one simply contributes no propagated spans."""
    clean = SoakDriver(SyncServer(), Scenario(CFG), flush_every=4).run()
    mesh = ReplicaMesh(
        [
            ("r0", SyncServer()),
            ("r1", SyncServer(protocol=Protocol(version=1))),
            ("r2", SyncServer()),
        ]
    )
    tracer.enabled = True
    try:
        tracer.clear()
        rep = FederatedSoakDriver(
            mesh, Scenario(CFG), sync_every=4, anti_entropy_every=8,
            canary_every=4,
        ).run()
    finally:
        tracer.enabled = False
        tracer.clear()
    assert rep["converged"], rep
    assert rep["state_digest"] == clean["state_digest"]
    assert rep["canary"]["availability_min"] == 1.0, rep["canary"]


# ------------------------------------------------- /fleet + concurrency


def _assert_untorn_exposition(text: str):
    """A merged exposition is torn iff a family's series appear outside
    its contiguous TYPE block: every TYPE header exactly once, every
    sample under the most recent header's family."""
    seen_types = []
    current = None
    for line in text.strip().splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            fam = line.split()[2]
            assert fam not in seen_types, f"family {fam} split: torn merge"
            seen_types.append(fam)
            current = fam
        else:
            name = line.split("{", 1)[0].split()[0]
            assert current is not None and name.startswith(current), (
                f"sample {name!r} outside its TYPE block {current!r}"
            )


def test_concurrent_fleet_and_snapshot_scrapes_mid_soak():
    """8 threads hammer `/fleet` + `/snapshot` + `/metrics` WHILE the
    federated soak mutates the mesh (the probe hook fires mid-schedule):
    every response parses, no torn exposition, no deadlock — the scrape
    plane reads live state without stopping the world."""
    mesh = ReplicaMesh([(f"r{i}", SyncServer()) for i in range(3)])
    telemetry = TelemetryServer(port=0)
    mesh.attach_telemetry(telemetry)
    telemetry.start()
    errors = []
    bodies = {"fleet": [], "snapshot": [], "metrics": []}

    def hammer():
        try:
            for _ in range(4):
                for path, key in (
                    ("/fleet", "fleet"),
                    ("/snapshot", "snapshot"),
                    ("/metrics", "metrics"),
                ):
                    status, body = _get(telemetry.port, path)
                    assert status == 200
                    bodies[key].append(body)
        except Exception as e:  # noqa: BLE001 - collected for the assert
            errors.append(f"{type(e).__name__}: {e}")

    def probe():
        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "scrape thread wedged: deadlock"

    try:
        rep = FederatedSoakDriver(
            mesh, Scenario(CFG), sync_every=4, anti_entropy_every=8,
            canary_every=4, probe_at=0.5, probe=probe,
        ).run()
    finally:
        telemetry.stop()
    assert not errors, errors
    assert rep["converged"]
    assert len(bodies["fleet"]) == 8 * 4
    for body in bodies["fleet"]:
        _assert_untorn_exposition(body)
        for rid in ("r0", "r1", "r2"):
            assert f'replica="{rid}"' in body
    for body in bodies["snapshot"]:
        snap = json.loads(body)  # valid JSON = not torn
        assert "fleet_timeline" in snap


def test_fleet_source_error_is_reported_not_fatal():
    t = TelemetryServer(port=0)
    t.add_fleet_source("good", lambda: {"replica.alive": 1.0})

    def bad():
        raise RuntimeError("boom")

    t.add_fleet_source("bad", bad)
    t.start()
    try:
        status, body = _get(t.port, "/fleet")
    finally:
        t.stop()
    assert status == 200
    assert 'replica_alive{replica="good"} 1' in body
    assert 'fleet_source_error{replica="bad"} 1' in body
    _assert_untorn_exposition(body)


# ------------------------------------------------------- canary + digest


def test_canary_tenants_stay_off_the_parity_surface():
    """Two servers with identical real-tenant state but different canary
    traffic must digest identically (CANARY_PREFIX exclusion)."""
    a, b = SyncServer(), SyncServer()
    for server in (a, b):
        server.connect_frames("t0")
    b.connect_frames(f"{CANARY_PREFIX}:r9")
    assert server_state_digest(a, "text") == server_state_digest(b, "text")


def test_timeline_records_ownership_and_migration():
    mesh = ReplicaMesh([("a", SyncServer()), ("b", SyncServer())])
    mesh.assign_owner("t0", "a")
    mesh.migrate_tenant("t0", "b")
    kinds = [ev["kind"] for ev in mesh.timeline_events()]
    assert "ownership" in kinds and "migration" in kinds, kinds
    seqs = [ev["seq"] for ev in mesh.timeline_events()]
    assert seqs == sorted(seqs)


# -------------------------------------------------- --compare-baseline


def test_compare_baseline_embeds_directional_verdict():
    import bench

    base = {"value": 1000.0, "soak": {"apply_p99_ms": 2.0}}
    same = bench._compare_baseline(dict(base), baseline=base)
    assert same["status"] == "compared" and same["exit_status"] == 0
    assert same["regressions"] == []
    worse = bench._compare_baseline(
        {"value": 500.0, "soak": {"apply_p99_ms": 9.0}}, baseline=base
    )
    assert worse["exit_status"] == 1
    keys = {r["key"] for r in worse["regressions"]}
    assert keys == {"value", "soak.apply_p99_ms"}
    # the verdict must degrade, never raise
    broken = bench._compare_baseline(
        {"value": object()}, baseline=base
    )
    assert broken["exit_status"] in (0, 1, 2)
