"""origin_slot cache invariant (VERDICT r4 #9 structural fix).

The YATA conflict scan's case-2 step must resolve each candidate's origin
to its containing slot (reference hot loop: block.rs:537-602).  Before the
cache, that was an O(capacity) `_find_slot` compare per while-trip — the
p99=337-candidate tail of the 256-client workload rode it.  The cache
contract, asserted here against a brute-force recompute:

  for every ACTIVE row with a stored origin whose containing block exists
  in the (shard-)local store, `blocks.origin_slot` is the slot of that
  block; -1 when the row has no origin or the origin is absent (e.g. a
  non-local origin on a shard).  Rows that never linked into a sequence
  (GC carriers, rows in error-flagged docs) may conservatively cache -1 —
  the scan never visits them as candidates.

Maintenance sites covered: insert (link-in), block splits (clean start/
end + delete-range + move-bound repair), squash/defragment compaction,
capacity growth, checkpoint save/load (incl. pre-origin_slot format-2
checkpoints), sharded link-in and rebalance, fused-lane unpack.
"""

from __future__ import annotations

import numpy as np
import pytest

from _fused_interpret import run_or_skip

from ytpu.core import Doc
from ytpu.core.update import Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_stream,
    init_state,
    recompute_origin_slot,
)


def _invariant_violations(state):
    """Compare the maintained origin_slot column against a brute-force
    recompute, demanding exact equality on every active slot.  (The
    unlinked-row carve-out — maintained -1 where a recompute would
    resolve — is covered by test_pallas_kernel.assert_same_state, whose
    workloads include GC carriers; these fixtures contain none.)"""
    recomputed = recompute_origin_slot(state)
    got = np.asarray(state.blocks.origin_slot)
    want = np.asarray(recomputed.blocks.origin_slot)
    D, B = got.shape
    n = np.asarray(state.n_blocks)
    active = np.arange(B)[None, :] < n[:, None]
    bad = active & (got != want)
    return [
        (int(d), int(s), int(got[d, s]), int(want[d, s]))
        for d, s in zip(*np.nonzero(bad))
    ]


def _replay(log, n_docs=4, capacity=256, rows=8, dels=8):
    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), rows, dels) for p in log]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    state = apply_update_stream(init_state(n_docs, capacity), stream, rank)
    assert not np.any(np.asarray(state.error)), "replay errored"
    return state, enc


def _concurrent_log(seed=7, n_ops=40):
    """Two peers editing the same text concurrently — the conflict-scan
    workload (case-1 ties and case-2 folds both exercised)."""
    rng = np.random.default_rng(seed)
    a, b = Doc(client_id=10), Doc(client_id=3)
    log = []
    a.observe_update_v1(lambda p, o, t: log.append(p))
    b.observe_update_v1(lambda p, o, t: log.append(p))
    ta, tb = a.get_text("text"), b.get_text("text")
    for i in range(n_ops):
        doc, t = (a, ta) if i % 2 == 0 else (b, tb)
        s = t.get_string()
        with doc.transact() as txn:
            if rng.random() < 0.25 and len(s) > 4:
                pos = int(rng.integers(0, len(s) - 2))
                t.remove_range(txn, pos, int(rng.integers(1, 3)))
            else:
                pos = int(rng.integers(0, len(s) + 1))
                t.insert(txn, pos, f"<{i}>")
        # exchange every few ops so both sides build on shared prefixes
        # (concurrent runs between exchanges create the YATA conflicts)
        if i % 5 == 4:
            sa = a.encode_state_as_update_v1(b.state_vector())
            sb = b.encode_state_as_update_v1(a.state_vector())
            a.apply_update_v1(sb)
            b.apply_update_v1(sa)
    sa = a.encode_state_as_update_v1(b.state_vector())
    sb = b.encode_state_as_update_v1(a.state_vector())
    a.apply_update_v1(sb)
    b.apply_update_v1(sa)
    assert a.get_text("text").get_string() == b.get_text("text").get_string()
    return log, a.get_text("text").get_string()


def test_cache_matches_recompute_after_concurrent_replay():
    log, expect = _concurrent_log()
    state, enc = _replay(log, capacity=512, rows=16, dels=16)
    assert _invariant_violations(state) == []
    from ytpu.models.batch_doc import get_string

    got = get_string(state, 0, enc.payloads)
    assert got == expect


def test_cache_survives_delete_range_splits():
    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    t = doc.get_text("text")
    with doc.transact() as txn:
        t.insert(txn, 0, "abcdefghijklmnop")  # one fat block
    with doc.transact() as txn:
        t.remove_range(txn, 4, 3)  # splits it mid-block twice
    with doc.transact() as txn:
        t.insert(txn, 6, "XYZ")
    with doc.transact() as txn:
        t.remove_range(txn, 0, 2)
    state, _ = _replay(log)
    assert _invariant_violations(state) == []


def test_cache_survives_compaction():
    jax = pytest.importorskip("jax")
    from ytpu.ops.compaction import compact_state

    log, _ = _concurrent_log(seed=11, n_ops=30)
    state, _ = _replay(log, capacity=512, rows=16, dels=16)
    compacted = compact_state(jax.tree_util.tree_map(lambda x: x, state))
    assert _invariant_violations(compacted) == []


def test_cache_survives_capacity_growth():
    from ytpu.ops.compaction import grow_state

    log, _ = _concurrent_log(seed=13, n_ops=20)
    state, _ = _replay(log, capacity=512, rows=16, dels=16)
    grown = grow_state(state, 1024)
    assert _invariant_violations(grown) == []


def test_checkpoint_roundtrip_and_format2_backcompat(tmp_path):
    from ytpu.models import checkpoint as ckpt

    log, _ = _concurrent_log(seed=17, n_ops=20)
    state, enc = _replay(log, capacity=512, rows=16, dels=16)

    path = str(tmp_path / "ck")
    ckpt.save_state(path, state, enc)
    restored, _ = ckpt.load_state(path)
    assert _invariant_violations(restored) == []

    # a format-2 checkpoint has no origin_slot column: strip it and mark
    # the sidecar format 2 — load must recompute the cache
    import os
    import pickle

    npz = os.path.join(path, "arrays.npz")
    if os.path.exists(npz):
        with np.load(npz, allow_pickle=False) as z:
            flat = {k: z[k] for k in z.files if k != "blocks.origin_slot"}
        np.savez_compressed(npz, **flat)
    else:  # orbax backend: rewrite as npz for the stripped copy
        import shutil

        flat = {
            f"blocks.{k}": np.asarray(v)
            for k, v in state.blocks._asdict().items()
            if k != "origin_slot"
        }
        flat["start"] = np.asarray(state.start)
        flat["n_blocks"] = np.asarray(state.n_blocks)
        flat["error"] = np.asarray(state.error)
        shutil.rmtree(os.path.join(path, "arrays"), ignore_errors=True)
        np.savez_compressed(npz, **flat)
    with open(os.path.join(path, "host.pkl"), "rb") as f:
        side = pickle.load(f)
    side["format"] = 2
    side["saved_with"] = "npz"
    with open(os.path.join(path, "host.pkl"), "wb") as f:
        pickle.dump(side, f)

    restored2, _ = ckpt.load_state(path)
    assert _invariant_violations(restored2) == []
    assert np.array_equal(
        np.asarray(restored2.blocks.origin_slot),
        np.asarray(recompute_origin_slot(restored2).blocks.origin_slot),
    )


def test_lazy_origin_slot_refresh_machinery():
    """ADVICE r5 #1: the O(D·B²) wholesale rebuild is LAZY — a state
    marked stale (the fused lane's unpack does this) is refreshed by
    `ensure_origin_slot`, and the XLA apply entry points do it
    implicitly before their conflict scan reads the cache. Verified
    here kernel-free by wiping + marking an XLA-lane state."""
    pytest.importorskip("jax")
    import jax.numpy as jnp

    from ytpu.models.batch_doc import (
        ensure_origin_slot,
        mark_origin_slot_stale,
        origin_slot_is_stale,
    )

    log, _ = _concurrent_log(seed=19, n_ops=24)
    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), 16, 16) for p in log]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    state, _enc2 = _replay(log, capacity=512, rows=16, dels=16)

    # simulate the fused unpack: cache plane wiped, state marked stale
    wiped = state._replace(
        blocks=state.blocks._replace(
            origin_slot=jnp.full_like(state.blocks.origin_slot, -1)
        )
    )
    assert not origin_slot_is_stale(wiped)
    mark_origin_slot_stale(wiped)
    assert origin_slot_is_stale(wiped)
    assert not origin_slot_is_stale(state)  # identity-keyed, no aliasing

    refreshed = ensure_origin_slot(wiped)
    assert not origin_slot_is_stale(refreshed)
    assert _invariant_violations(refreshed) == []
    # ensure on a never-stale state is a no-op passthrough
    assert ensure_origin_slot(refreshed) is refreshed

    # chaining into the XLA lane refreshes implicitly (the reader's
    # entry point calls ensure_origin_slot before the conflict scan);
    # a no-op step proves the refresh without re-integrating rows
    noop = BatchEncoder.stack_steps(
        [
            steps[0]._replace(
                valid=jnp.zeros_like(steps[0].valid),
                del_valid=jnp.zeros_like(steps[0].del_valid),
            )
        ]
    )
    chained = apply_update_stream(wiped, noop, rank)
    assert _invariant_violations(chained) == []


def test_fused_lane_default_defers_and_marks_stale():
    """End-to-end fused contract: default refresh_cache=False marks the
    unpacked state stale; refresh_cache=True keeps the eager rebuild.
    Skips where interpret-mode Pallas cannot run (jax builds missing
    discharge rules — the kernel itself is hardware-validated)."""
    pytest.importorskip("jax")
    from ytpu.models.batch_doc import (
        ensure_origin_slot,
        origin_slot_is_stale,
    )
    from ytpu.ops.integrate_kernel import apply_update_stream_fused

    log, _ = _concurrent_log(seed=19, n_ops=24)
    enc = BatchEncoder()
    steps = [enc.build_step(Update.decode_v1(p), 16, 16) for p in log]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    fused = run_or_skip(lambda: apply_update_stream_fused(
        init_state(4, 512), stream, rank, d_block=2, interpret=True
    ))
    assert origin_slot_is_stale(fused)
    assert _invariant_violations(ensure_origin_slot(fused)) == []
    eager = apply_update_stream_fused(
        init_state(4, 512), stream, rank, d_block=2, interpret=True,
        refresh_cache=True,
    )
    assert not origin_slot_is_stale(eager)
    assert _invariant_violations(eager) == []


def test_sharded_cache_is_minus_one_only_for_nonlocal_origins():
    from ytpu.parallel.sharded_doc import ShardedDoc

    doc = Doc(client_id=5)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    t = doc.get_text("text")
    words = [f"w{i} " for i in range(60)]
    for i, w in enumerate(words):
        with doc.transact() as txn:
            t.insert(txn, (i * 3) % max(1, len(t.get_string())), w)
    sd = ShardedDoc(n_shards=4, capacity=1024)
    for p in log:
        sd.apply_update_v1(p)
    sd.flush()
    state = sd.state
    viols = _invariant_violations(state)
    assert viols == [], viols

    sd.rebalance()
    assert _invariant_violations(sd.state) == [], "rebalance broke the cache"
    assert sd.get_string() == doc.get_text("text").get_string()
