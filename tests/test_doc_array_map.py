"""Array / Map / nested type semantics.

Model: reference types/array.rs:653-940 and types/map.rs:640-1112 tests.
"""

from ytpu.core import Doc
from ytpu.types import ArrayPrelim, MapPrelim, TextPrelim


def exchange(a: Doc, b: Doc) -> None:
    ua = a.encode_state_as_update_v1(b.state_vector())
    ub = b.encode_state_as_update_v1(a.state_vector())
    b.apply_update_v1(ua)
    a.apply_update_v1(ub)


def test_array_insert_get():
    d = Doc(client_id=1)
    arr = d.get_array("a")
    with d.transact() as txn:
        arr.insert_range(txn, 0, [1, 2, "three", True, None])
    assert arr.to_list() == [1, 2, "three", True, None]
    assert arr.get(2) == "three"
    assert len(arr) == 5


def test_array_remove():
    d = Doc(client_id=1)
    arr = d.get_array("a")
    with d.transact() as txn:
        arr.insert_range(txn, 0, list(range(10)))
    with d.transact() as txn:
        arr.remove_range(txn, 2, 5)
    assert arr.to_list() == [0, 1, 7, 8, 9]


def test_array_concurrent_converge():
    a, b = Doc(client_id=1), Doc(client_id=2)
    aa, ab = a.get_array("a"), b.get_array("a")
    with a.transact() as txn:
        aa.insert_range(txn, 0, [0, 0, 0])
    exchange(a, b)
    with a.transact() as txn:
        aa.insert(txn, 1, "a")
    with b.transact() as txn:
        ab.insert(txn, 1, "b")
        ab.remove(txn, 0)
    exchange(a, b)
    assert aa.to_list() == ab.to_list()


def test_map_set_get_remove():
    d = Doc(client_id=1)
    m = d.get_map("m")
    with d.transact() as txn:
        m.insert(txn, "k1", "v1")
        m.insert(txn, "k2", 42)
    assert m.get("k1") == "v1"
    assert m.get("k2") == 42
    with d.transact() as txn:
        m.insert(txn, "k1", "v1b")  # overwrite
        m.remove(txn, "k2")
    assert m.get("k1") == "v1b"
    assert m.get("k2") is None
    assert m.to_json() == {"k1": "v1b"}


def test_map_concurrent_higher_actor_wins():
    """Conflict rule: for concurrent map writes the higher client id wins
    (reference: lib.rs:427-430)."""
    a, b = Doc(client_id=1), Doc(client_id=2)
    ma, mb = a.get_map("m"), b.get_map("m")
    with a.transact() as txn:
        ma.insert(txn, "k", "from_a")
    with b.transact() as txn:
        mb.insert(txn, "k", "from_b")
    exchange(a, b)
    assert ma.get("k") == mb.get("k") == "from_b"


def test_map_sequential_last_writer_wins():
    a, b = Doc(client_id=5), Doc(client_id=2)
    ma, mb = a.get_map("m"), b.get_map("m")
    with a.transact() as txn:
        ma.insert(txn, "k", "first")
    exchange(a, b)
    with b.transact() as txn:
        mb.insert(txn, "k", "second")  # causally after: must win despite lower id
    exchange(a, b)
    assert ma.get("k") == mb.get("k") == "second"


def test_nested_array_in_map():
    d = Doc(client_id=1)
    m = d.get_map("m")
    with d.transact() as txn:
        m.insert(txn, "list", ArrayPrelim([1, 2, 3]))
    nested = m.get("list")
    assert nested.to_list() == [1, 2, 3]
    assert d.to_json() == {"m": {"list": [1, 2, 3]}}


def test_nested_types_sync():
    a, b = Doc(client_id=1), Doc(client_id=2)
    ma = a.get_map("m")
    with a.transact() as txn:
        ma.insert(txn, "txt", TextPrelim("hello"))
        ma.insert(txn, "cfg", MapPrelim({"x": 1}))
    exchange(a, b)
    mb = b.get_map("m")
    assert mb.get("txt").get_string() == "hello"
    assert mb.get("cfg").to_json() == {"x": 1}
    # mutate nested type on b, sync back
    with b.transact() as txn:
        mb.get("txt").insert(txn, 5, " world")
    exchange(a, b)
    assert ma.get("txt").get_string() == "hello world"


def test_deep_nesting_delete():
    d = Doc(client_id=1)
    m = d.get_map("root")
    with d.transact() as txn:
        m.insert(txn, "a", MapPrelim({"b": ArrayPrelim([TextPrelim("deep")])}))
    inner = m.get("a").get("b").get(0)
    assert inner.get_string() == "deep"
    with d.transact() as txn:
        m.remove(txn, "a")
    assert m.get("a") is None
    assert d.to_json() == {"root": {}}


def test_binary_payload():
    a, b = Doc(client_id=1), Doc(client_id=2)
    arr = a.get_array("a")
    with a.transact() as txn:
        arr.push_back(txn, b"\x01\x02\xff")
    exchange(a, b)
    assert b.get_array("a").to_list() == [b"\x01\x02\xff"]


def test_xml_tree_navigation():
    from ytpu.types import XmlElementPrelim, XmlTextPrelim

    d = Doc(client_id=1)
    frag = d.get_xml_fragment("f")
    with d.transact() as txn:
        frag.insert_range(
            txn,
            0,
            [
                XmlElementPrelim("div", children=[XmlElementPrelim("span"), XmlTextPrelim("hi")]),
                XmlTextPrelim("tail"),
            ],
        )
    div = frag.first_child()
    assert div.tag == "div"
    span = div.first_child()
    assert span.tag == "span"
    assert span.next_sibling().get_string() == "hi"
    assert div.next_sibling().get_string() == "tail"
    assert div.next_sibling().prev_sibling().tag == "div"
    assert span.parent().tag == "div"
    # depth-first walk
    tags = []
    for node in frag.successors():
        tags.append(getattr(node, "tag", None) or node.get_string())
    assert tags == ["div", "span", "hi", "tail"]
