"""Multi-replica federation (ISSUE-13): peer sync mesh, O(1) incremental
state commitments, partition/heal chaos, forced failover.

Layering: the protocol/commitment/mesh tests are HOST-ONLY (no jax —
`SyncServer` replicas; milliseconds), the device-backed mesh test reuses
the suite-wide (n_docs=4, capacity=256) `DeviceSyncServer` family
compiled by test_device_server/test_serving_soak, and the commitment
lane-agreement test reuses test_async_overlap's (2, 256, 16) replay
family (fused interpret via `_fused_interpret.run_or_skip`).
"""

import urllib.request

import pytest

from _fused_interpret import run_or_skip

from ytpu.core import Doc
from ytpu.serving import (
    FederatedSoakDriver,
    Scenario,
    ScenarioConfig,
    SoakDriver,
)
from ytpu.serving.soak import server_state_digest
from ytpu.sync.commitment import (
    MASK32,
    TenantCommitments,
    commitment_of_clocks,
    device_commit_of_clocks,
)
from ytpu.sync.protocol import (
    Message,
    OwnershipHandoff,
    SyncMessage,
    commit_message,
    decode_commit,
    decode_ownership,
    message_reader,
    ownership_message,
)
from ytpu.sync.replica import DivergenceFault, ReplicaMesh
from ytpu.sync.server import SyncServer
from ytpu.utils import metrics
from ytpu.utils.faults import faults

CFG = ScenarioConfig(n_tenants=3, n_sessions=8, events_per_session=8, seed=5)


def _clean_digest() -> str:
    """The PR-9 oracle: the scenario's clean single-server digest."""
    return SoakDriver(SyncServer(), Scenario(CFG), flush_every=4).run()[
        "state_digest"
    ]


def _write(server, tenant: str, doc: Doc, text: str, at: int = 0) -> None:
    """One client edit delivered to `server` as a protocol update frame."""
    sess, _ = server.connect_frames(tenant)
    with doc.transact() as txn:
        doc.get_text("text").insert(txn, at, text)
    upd = doc.encode_state_as_update_v1()
    server.receive_frames(
        sess, Message.sync(SyncMessage.update(upd)).encode_v1()
    )
    server.disconnect(sess)


# --------------------------------------------------------------- protocol


def test_commit_and_ownership_frame_round_trip():
    big = 0xDEADBEEF_CAFEF00D  # exercises the 64-bit lo/hi split
    msg = commit_message("tenant0", big, round_=7)
    frame = msg.encode_v1()
    (decoded,) = list(message_reader(frame))
    assert decoded.kind == 5
    assert decode_commit(decoded.body) == ("tenant0", big, 7)

    h = OwnershipHandoff("tenant1", "replica-b", 42)
    frame = ownership_message(h).encode_v1()
    (decoded,) = list(message_reader(frame))
    assert decoded.kind == 6
    assert decode_ownership(decoded.body) == h


# ------------------------------------------------------------- commitment


def test_commitment_incremental_equals_full_and_is_order_free():
    tc = TenantCommitments()
    # fold in three deltas, out of client order, across calls
    tc.refresh("t", [(7, 3)])
    tc.refresh("t", [(7, 3), (123456, 10)])
    inc = tc.refresh("t", [(7, 9), (123456, 10), (2, 1)])
    assert inc == commitment_of_clocks({2: 1, 7: 9, 123456: 10})
    # order independence of the full fold (additive homomorphism)
    assert commitment_of_clocks({7: 9, 2: 1, 123456: 10}) == inc
    # a shrunk clock (checkpoint-restored server) forces a clean rebuild
    # from the sv as given — the tracker mirrors the server, not history
    assert tc.refresh("t", [(7, 4)]) == commitment_of_clocks({7: 4})


def test_commit_corrupt_poisons_the_incremental_fold_stickily():
    faults.clear()
    spec = faults.arm("commit.corrupt")
    try:
        tc = TenantCommitments()
        poisoned = tc.refresh("t", [(7, 5)])
    finally:
        faults.clear()
    assert spec.fired == 1
    truth = commitment_of_clocks({7: 5})
    assert poisoned != truth
    # sticky: later (un-injected) folds keep the divergence — nothing
    # re-derives the poisoned prefix...
    assert tc.refresh("t", [(7, 8)]) != commitment_of_clocks({7: 8})
    # ...except the authoritative recompute (the recovery path)
    assert tc.recompute("t", [(7, 8)]) == commitment_of_clocks({7: 8})


# ---------------------------------------------------- anti-entropy + mesh


def test_anti_entropy_compares_commitments_and_pulls_only_on_mismatch():
    a, b = SyncServer(), SyncServer()
    mesh = ReplicaMesh([("a", a), ("b", b)], tenants=["room"])
    mesh.sync_round()
    # agreement round: one O(1) probe each way, nothing pulled
    rep = mesh.anti_entropy_round()
    assert rep["compared"] >= 1 and rep["mismatches"] == 0, rep
    # diverge replica a only (no sync round in between)
    _write(a, "room", Doc(client_id=301), "only-on-a ")
    rep = mesh.anti_entropy_round()
    assert rep["mismatches"] >= 1 and rep["pulled"] >= 1, rep
    assert rep["divergences"] == 0, rep
    mismatch_bytes = rep["bytes"]
    assert b.doc("room").get_text("text").get_string() == "only-on-a "
    # repaired: back to the cheap path — an agreement round costs only
    # the two commit probes (the O(1) claim, in bytes), strictly less
    # than the round that had to pull the SV-diff
    rep = mesh.anti_entropy_round()
    assert rep["mismatches"] == 0, rep
    assert 0 < rep["bytes"] < min(mismatch_bytes, 64), (rep, mismatch_bytes)


def test_partition_heal_converges_to_scenario_oracle():
    clean = _clean_digest()
    mesh = ReplicaMesh([("r0", SyncServer()), ("r1", SyncServer())])
    rep = FederatedSoakDriver(
        mesh,
        Scenario(CFG),
        sync_every=6,
        anti_entropy_every=10,
        partition_at=0.25,
        heal_at=0.6,
    ).run()
    assert rep["partitions"] >= 1 and rep["heals"] >= 1, rep
    assert rep["converged"], rep
    assert rep["state_digest"] == clean, rep
    assert set(rep["replica_digests"]) == {"r0", "r1"}
    assert len(set(rep["replica_digests"].values())) == 1


def test_forced_failover_sessions_reconnect_and_ownership_migrates():
    clean = _clean_digest()
    dropped_before = metrics.counter(
        "net.sessions_dropped", labelnames=("reason",)
    ).labels("failover").value
    mesh = ReplicaMesh([(f"r{i}", SyncServer()) for i in range(3)])
    rep = FederatedSoakDriver(
        mesh,
        Scenario(CFG),
        sync_every=6,
        anti_entropy_every=12,
        failover_at=0.7,
        failover_replica="r2",
    ).run()
    assert rep["failovers"] == 1, rep
    assert not mesh.replicas["r2"].alive
    assert rep["failover_sessions_dropped"] >= 1, rep
    assert rep["failover_reconnects"] >= 1, rep
    # the metric carries the attribution (reason="failover")
    dropped = metrics.counter(
        "net.sessions_dropped", labelnames=("reason",)
    ).labels("failover").value - dropped_before
    assert dropped == rep["failover_sessions_dropped"], (dropped, rep)
    # every tenant's owner is a survivor, epoch bumped past the handoff
    for tenant, (owner, epoch) in mesh.owner.items():
        assert owner != "r2", (tenant, owner)
        assert mesh.replicas[owner].alive
    # survivors hold the oracle state — convergence re-established
    assert rep["converged"] and rep["state_digest"] == clean, rep


def test_migration_is_typed_epoch_guarded_handoff():
    mesh = ReplicaMesh(
        [("a", SyncServer()), ("b", SyncServer())], tenants=["room"]
    )
    doc = Doc(client_id=401)
    _write(mesh.replicas["a"].server, "room", doc, "pre-migration ")
    epoch = mesh.migrate_tenant("room", "b")
    assert mesh.owner["room"] == ("b", epoch)
    assert mesh.route("room").id == "b"
    # a stale handoff (≤ current epoch) must be ignored, not applied
    assert not mesh._apply_handoff(OwnershipHandoff("room", "a", epoch))
    assert mesh.owner["room"][0] == "b"
    # migration drained first: the new owner already holds the state
    assert (
        mesh.replicas["b"].server.doc("room").get_text("text").get_string()
        == "pre-migration "
    )


def test_replica_lag_defers_but_loses_nothing():
    a, b = SyncServer(), SyncServer()
    mesh = ReplicaMesh([("a", a), ("b", b)], tenants=["room"])
    mesh.sync_round()
    faults.clear()
    spec = faults.arm("replica.lag", rounds=2)
    try:
        _write(a, "room", Doc(client_id=501), "laggy ")
        mesh.sync_round()  # fires the site: delivery deferred
        assert spec.fired == 1
        assert b.doc("room").get_text("text").get_string() == ""
        for _ in range(3):
            mesh.sync_round()
        assert b.doc("room").get_text("text").get_string() == "laggy "
    finally:
        faults.clear()


def test_partition_and_heal_fault_sites_via_grammar():
    a, b = SyncServer(), SyncServer()
    mesh = ReplicaMesh([("a", a), ("b", b)], tenants=["room"])
    mesh.sync_round()
    faults.clear()
    faults.configure("replica.partition;replica.heal:after=1")
    try:
        _write(a, "room", Doc(client_id=601), "dropped? ")
        mesh.sync_round()  # partition fires: the frame is DROPPED
        assert b.doc("room").get_text("text").get_string() == ""
        assert (
            metrics.counter(
                "replica.frames_dropped", labelnames=("reason",)
            ).labels("partition").value
            >= 1
        )
        mesh.sync_round()  # heal fires: gossip queues the SV resync
        mesh.sync_round()
        assert b.doc("room").get_text("text").get_string() == "dropped? "
    finally:
        faults.clear()


def test_bare_mesh_sync_rounds_quiesce():
    """A ≥3-replica mesh with no client traffic must reach quiescence:
    awareness snapshots are rebroadcast unconditionally by servers, so
    without the per-replica payload dedup covering them one snapshot
    would circulate the triangle forever and every sync round would
    burn its full pass budget (review-caught liveness pin)."""
    mesh = ReplicaMesh(
        [(f"r{i}", SyncServer()) for i in range(3)], tenants=["room"]
    )
    mesh.sync_round()  # greetings + their fan-out settle here
    rep = mesh.sync_round()
    assert rep["frames"] == 0 and rep["passes"] == 1, rep


def test_silently_dropped_update_is_not_blacklisted():
    """An update the receiving server REFUSED without any reply
    (admission policy="drop") must not enter the dedup set: the
    mark-on-success gate reads the applied counter, so the SV-resync
    retransmission — byte-identical payload — still lands (review-caught
    correctness pin)."""
    from ytpu.serving import AdmissionController

    a, b = SyncServer(), SyncServer()
    mesh = ReplicaMesh([("a", a), ("b", b)], tenants=["room"])
    mesh.sync_round()
    b.admission = AdmissionController(policy="drop")
    _write(a, "room", Doc(client_id=801), "must-arrive ")
    faults.clear()
    spec = faults.arm("admission.reject", n=1)
    try:
        mesh.sync_round()  # the update crosses the link and is refused
    finally:
        faults.clear()
    assert spec.fired == 1
    assert b.doc("room").get_text("text").get_string() == ""
    b.admission = None
    rep = mesh.anti_entropy_round()
    assert rep["mismatches"] >= 1 and rep["pulled"] >= 1, rep
    assert b.doc("room").get_text("text").get_string() == "must-arrive "


# ------------------------------------------- divergence + health surface


def test_commit_corrupt_divergence_quarantines_and_degrades_healthz():
    from ytpu.utils.telemetry import TelemetryServer

    a, b = SyncServer(), SyncServer()
    mesh = ReplicaMesh([("a", a), ("b", b)], tenants=["room"])
    mesh.sync_round()
    faults.clear()
    spec = faults.arm("commit.corrupt")
    try:
        _write(a, "room", Doc(client_id=701), "diverge-me ")
        mesh.sync_round()  # replicas converge; one tracker gets poisoned
        div_before = metrics.counter("replica.divergences").value
        with pytest.raises(DivergenceFault) as exc:
            mesh.anti_entropy_round(strict=True)
        assert spec.fired == 1
        assert exc.value.tenant == "room"
        assert "room" in mesh.quarantined
        assert metrics.counter("replica.divergences").value == div_before + 1
        # /healthz surfaces it: degraded + the tenant named
        with TelemetryServer(port=0) as t:
            mesh.attach_health(t)
            import json

            body = json.loads(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{t.port}/healthz", timeout=5
                ).read()
            )
        assert body["status"] == "degraded", body
        assert body["replica"]["quarantined_tenants"] == ["room"], body
        # quarantined tenants are skipped by later rounds
        assert mesh.anti_entropy_round()["tenants"] == 0
        # recovery: authoritative rebuild clears the poison + quarantine
        rec_before = metrics.counter("replica.recoveries").value
        assert mesh.recover_tenant("room")
        assert not mesh.quarantined
        assert metrics.counter("replica.recoveries").value == rec_before + 1
        assert mesh.anti_entropy_round()["mismatches"] == 0
    finally:
        faults.clear()


# ---------------------------------------------------- device-backed mesh


def test_device_backed_mesh_federates_at_oracle_parity():
    pytest.importorskip("jax")
    from ytpu.sync.device_server import DeviceSyncServer

    cfg = ScenarioConfig(
        n_tenants=2, n_sessions=4, events_per_session=6, seed=29
    )
    clean = SoakDriver(
        DeviceSyncServer(n_docs=4, capacity=256), Scenario(cfg),
        flush_every=4,
    ).run()
    mesh = ReplicaMesh(
        [
            ("a", DeviceSyncServer(n_docs=4, capacity=256)),
            ("b", DeviceSyncServer(n_docs=4, capacity=256)),
        ]
    )
    rep = FederatedSoakDriver(
        mesh, Scenario(cfg), sync_every=4, anti_entropy_every=8,
        partition_at=0.3, heal_at=0.6,
    ).run()
    assert rep["converged"], rep
    assert rep["state_digest"] == clean["state_digest"], rep
    # the digest parity is DEVICE-rendered on both sides (slotted
    # tenants render via device_text inside server_state_digest)
    for rid in ("a", "b"):
        server = mesh.replicas[rid].server
        assert server_state_digest(server, cfg.root) == clean["state_digest"]
        for tenant in sorted(server.tenants):
            server.device_text(tenant)  # KeyError would mean host-demoted


# -------------------------------------------- device commitment readout


@pytest.fixture(scope="module")
def _multi_client_log():
    """A 3-writer shared-doc history (clients 3/5/9, inserts + deletes)
    in causal order — every lane must fold the same lattice."""
    pytest.importorskip("jax")
    docs = {c: Doc(client_id=c) for c in (3, 5, 9)}
    captured = []

    def capture(p, origin, txn):
        if origin != "relay":
            captured.append(p)

    for d in docs.values():
        d.observe_update_v1(capture)
    log = []
    for k in range(8):
        for c, d in docs.items():
            for p in log:
                d.apply_update_v1(p, origin="relay")
            txt = d.get_text("text")
            with d.transact() as txn:
                cur = txt.get_string()
                if len(cur) > 10 and (k + c) % 3 == 0:
                    txt.remove_range(txn, 2, 4)
                else:
                    txt.insert(txn, min(len(cur), c), f"c{c}k{k}")
            log.append(captured[-1])
    oracle = Doc(client_id=99)
    for p in log:
        oracle.apply_update_v1(p)
    return log, dict(oracle.state_vector()), oracle.get_text(
        "text"
    ).get_string()


def _replay(log, lane, interpret=False):
    from ytpu.models.replay import FusedReplay, plan_replay

    return FusedReplay(
        n_docs=2,
        plan=plan_replay(log),
        capacity=256,
        max_capacity=256,
        d_block=2,
        chunk=16,
        lane=lane,
        interpret=interpret,
        overlap=True,
    )


def test_commitment_readout_word_matches_sv_closed_form(_multi_client_log):
    """The device commitment word (the new last word of the lazy
    readout) equals the pure-Python closed form over the final state
    vector — the block rows tile each client's lattice, so the
    row-wise fold collapses to `device_commit_of_clocks`."""
    from ytpu.native import available as native_available

    if not native_available():
        pytest.skip("native codec unavailable (plan pre-scan)")
    log, sv, expect_text = _multi_client_log
    r = _replay(log, "xla")
    stats = r.run(log)
    assert r.get_string(0) == expect_text
    per_doc = device_commit_of_clocks(sv)
    assert stats.commit_word == (2 * per_doc) & MASK32, (
        stats.commit_word, per_doc, sv,
    )
    # the host federation mirror folds the SAME lattice (64-bit params,
    # same clock coverage): its incremental and full values agree on it
    tc = TenantCommitments()
    assert tc.refresh("t", sv.items()) == commitment_of_clocks(sv)


def test_commitment_readout_word_agrees_across_lanes(_multi_client_log):
    """serial-oracle (closed form) / packed-XLA / fused-interpret land
    the identical commitment word; `packed_commitments` exposes the
    per-doc words behind the aggregate."""
    import numpy as np

    from ytpu.native import available as native_available
    from ytpu.ops.integrate_kernel import packed_commitments

    if not native_available():
        pytest.skip("native codec unavailable (plan pre-scan)")
    log, sv, _ = _multi_client_log
    per_doc = device_commit_of_clocks(sv)
    xla = _replay(log, "xla")
    s_xla = xla.run(log)

    def fused():
        r = _replay(log, "fused", interpret=True)
        return r.run(log)

    s_fused = run_or_skip(fused)
    assert s_xla.commit_word == s_fused.commit_word == (2 * per_doc) & MASK32
    # per-doc pull: both docs carry the identical broadcast stream
    words = np.asarray(packed_commitments(xla.cols, xla.meta)).astype(
        np.uint32
    )
    assert list(words) == [per_doc, per_doc], (words, per_doc)
