"""Snapshots / time travel.

Model: reference store.rs:139-184 (encode_state_from_snapshot),
transaction.rs:986-1018 (split_by_snapshot), text snapshot diffs.
"""

import pytest

from ytpu.core import Doc, Snapshot


def test_snapshot_roundtrip_wire():
    d = Doc(client_id=1, skip_gc=True)
    t = d.get_text("t")
    with d.transact() as txn:
        t.insert(txn, 0, "hello")
    snap = d.snapshot()
    data = snap.encode_v1()
    out = Snapshot.decode_v1(data)
    assert out == snap


def test_encode_state_from_snapshot():
    d = Doc(client_id=1, skip_gc=True)
    t = d.get_text("t")
    with d.transact() as txn:
        t.insert(txn, 0, "hello")
    snap = d.snapshot()
    with d.transact() as txn:
        t.insert(txn, 5, " world")
        t.remove_range(txn, 0, 1)  # "ello world"
    assert t.get_string() == "ello world"
    historical = d.encode_state_from_snapshot(snap)
    replica = Doc(client_id=2)
    replica.apply_update_v1(historical)
    assert replica.get_text("t").get_string() == "hello"


def test_encode_state_from_snapshot_requires_skip_gc():
    d = Doc(client_id=1)  # gc enabled
    t = d.get_text("t")
    with d.transact() as txn:
        t.insert(txn, 0, "x")
    snap = d.snapshot()
    with pytest.raises(RuntimeError):
        d.encode_state_from_snapshot(snap)


def test_get_string_at_snapshot():
    d = Doc(client_id=1, skip_gc=True)
    t = d.get_text("t")
    with d.transact() as txn:
        t.insert(txn, 0, "version one")
    snap1 = d.snapshot()
    with d.transact() as txn:
        t.remove_range(txn, 8, 3)
        t.insert(txn, 8, "two")
    snap2 = d.snapshot()
    with d.transact() as txn:
        t.insert(txn, 0, "THE ")
    assert t.get_string() == "THE version two"
    with d.transact() as txn:
        assert t.get_string_at(txn, snap1) == "version one"
        assert t.get_string_at(txn, snap2) == "version two"


def test_snapshot_of_multiple_clients():
    a, b = Doc(client_id=1, skip_gc=True), Doc(client_id=2, skip_gc=True)
    ta, tb = a.get_text("t"), b.get_text("t")
    with a.transact() as txn:
        ta.insert(txn, 0, "aaa")
    b.apply_update_v1(a.encode_state_as_update_v1())
    with b.transact() as txn:
        tb.insert(txn, 3, "bbb")
    a.apply_update_v1(b.encode_state_as_update_v1(a.state_vector()))
    snap = a.snapshot()
    with a.transact() as txn:
        ta.insert(txn, 6, "ccc")
    historical = a.encode_state_from_snapshot(snap)
    replica = Doc(client_id=9)
    replica.apply_update_v1(historical)
    assert replica.get_text("t").get_string() == "aaabbb"
