"""Device-backed sync server: protocol tenants mirrored into batch slots."""

from ytpu.core import Doc
from ytpu.sync.device_server import DeviceSyncServer
from ytpu.sync.protocol import Message, SyncMessage


def push(server, session, peer_doc):
    sv = server.doc(session.tenant).state_vector()
    diff = peer_doc.encode_state_as_update_v1(sv)
    server.receive(session, Message.sync(SyncMessage.update(diff)).encode_v1())


def test_tenants_fan_into_device_slots():
    server = DeviceSyncServer(n_docs=4, capacity=256)
    s_pad, _ = server.connect("pad")
    s_doc, _ = server.connect("docs")

    alice = Doc(client_id=1)
    with alice.transact() as txn:
        alice.get_text("text").insert(txn, 0, "alice writes")
    push(server, s_pad, alice)

    bob = Doc(client_id=2)
    with bob.transact() as txn:
        bob.get_text("text").insert(txn, 0, "bob too")
    push(server, s_doc, bob)

    assert server.pending_device_updates() == 2
    steps = server.flush_device()
    assert steps == 1  # both tenants ship in ONE batch step
    assert server.pending_device_updates() == 0
    assert int(server.ingestor.state.error.max()) == 0
    assert server.device_text("pad") == "alice writes" == server.doc("pad").get_text("text").get_string()
    assert server.device_text("docs") == "bob too"


def test_chatty_tenant_does_not_block_quiet_one():
    server = DeviceSyncServer(n_docs=2, capacity=512)
    s_a, _ = server.connect("chatty")
    peer = Doc(client_id=5)
    for i in range(6):
        with peer.transact() as txn:
            t = peer.get_text("text")
            t.insert(txn, t.branch.content_len, f"{i}")
        push(server, s_a, peer)

    s_b, _ = server.connect("quiet")
    other = Doc(client_id=6)
    with other.transact() as txn:
        other.get_text("text").insert(txn, 0, "q")
    push(server, s_b, other)

    steps = server.flush_device()
    assert steps >= 1
    assert server.device_text("chatty") == "012345"
    assert server.device_text("quiet") == "q"
    assert int(server.ingestor.state.error.max()) == 0


def test_concurrent_sessions_converge_on_device():
    server = DeviceSyncServer(n_docs=1, capacity=512)
    s1, _ = server.connect("room")
    s2, _ = server.connect("room")
    a, b = Doc(client_id=11), Doc(client_id=22)
    for d, text in ((a, "left "), (b, "right ")):
        with d.transact() as txn:
            d.get_text("text").insert(txn, 0, text)
    push(server, s1, a)
    push(server, s2, b)
    server.flush_device()
    assert server.device_text("room") == server.doc("room").get_text("text").get_string()


def test_slot_exhaustion_raises():
    import pytest

    server = DeviceSyncServer(n_docs=1, capacity=64)
    server.connect("one")
    with pytest.raises(RuntimeError):
        server.connect("two")


def test_slot_exhaustion_retry_still_raises_and_leaves_no_ghost():
    import pytest

    server = DeviceSyncServer(n_docs=1, capacity=64)
    server.connect("one")
    with pytest.raises(RuntimeError):
        server.connect("two")
    assert "two" not in server.tenants  # no ghost tenant registered
    with pytest.raises(RuntimeError):
        server.connect("two")  # retry fails identically


def test_unknown_tenant_read_raises_instead_of_allocating():
    import pytest

    server = DeviceSyncServer(n_docs=2, capacity=64)
    server.connect("pad")
    with pytest.raises(KeyError):
        server.device_text("padd")  # typo: no silent slot allocation
    assert len(server._slot_of) == 1


def test_ingestor_is_slot_authority():
    from ytpu.models.ingest import BatchIngestor

    ing = BatchIngestor(3, 64)
    server = DeviceSyncServer(ingestor=ing)  # n_docs not needed
    for name in ("a", "b", "c"):
        server.connect(name)
    import pytest

    with pytest.raises(RuntimeError):
        server.connect("d")


def test_device_diff_formatted_tenant():
    from ytpu.sync.device_server import DeviceSyncServer

    srv = DeviceSyncServer(n_docs=2, capacity=256)
    t = srv.tenant("doc")
    doc = t.awareness.doc
    txt = doc.get_text("text")
    with doc.transact() as txn:
        txt.insert(txn, 0, "plain ")
    with doc.transact() as txn:
        txt.insert_with_attributes(txn, 6, "bold", {"b": True})
    srv.flush_device()
    got = srv.device_diff("doc")
    assert got == txt.diff(), f"{got!r} != {txt.diff()!r}"


def _client_pump(doc: Doc, server, session, client_frames: bytes) -> None:
    """Drive one client side of the y-sync handshake: process the server's
    frames against a local Doc and deliver replies back."""
    from ytpu.sync.protocol import Protocol, message_reader

    proto = Protocol()

    class _A:  # minimal awareness shim around the client doc
        def __init__(self, d):
            self.doc = d

        def update(self):
            from ytpu.sync.awareness import Awareness

            return Awareness(self.doc).update()

        def apply_update(self, u):
            pass

    aw = _A(doc)
    out = []
    for msg in message_reader(client_frames):
        reply = proto.handle_message(aw, msg)
        if reply is not None:
            out.append(reply.encode_v1())
    if out:
        server.receive(session, b"".join(out))


def test_device_authoritative_serving_converges_without_host_doc():
    """VERDICT r1 #7: sync step 2 answered from device state; the host
    tenant doc is demoted to an awareness anchor and never sees content."""
    server = DeviceSyncServer(n_docs=2, capacity=512, device_authoritative=True)

    # client A writes, connects, pushes its state as an update
    alice = Doc(client_id=1)
    with alice.transact() as txn:
        alice.get_text("text").insert(txn, 0, "hello from alice")
    s_a, greeting_a = server.connect("pad")
    _client_pump(alice, server, s_a, greeting_a)  # step1 -> client step2
    server.receive(
        s_a,
        Message.sync(
            SyncMessage.update(alice.encode_state_as_update_v1())
        ).encode_v1(),
    )
    server.flush_device()
    assert server.device_text("pad") == "hello from alice"

    # the host tenant doc never saw content (device-authoritative)
    assert server.doc("pad").get_text("text").get_string() == ""

    # client B connects fresh: sends step1, receives the device diff
    bob = Doc(client_id=2)
    s_b, greeting_b = server.connect("pad")
    _client_pump(bob, server, s_b, greeting_b)
    from ytpu.core.state_vector import StateVector
    from ytpu.sync.protocol import message_reader

    reply = server.receive(
        s_b, Message.sync(SyncMessage.step1(StateVector())).encode_v1()
    )
    for msg in message_reader(reply):
        assert msg.kind == 0 and msg.body.tag == 1  # SyncStep2
        bob.apply_update_v1(msg.body.payload)
    assert bob.get_text("text").get_string() == "hello from alice"

    # live edit from B broadcasts to A and lands on device
    with bob.transact() as txn:
        bob.get_text("text").insert(txn, 0, ">> ")
    sv_dev = server.device_state_vector("pad")
    server.receive(
        s_b,
        Message.sync(
            SyncMessage.update(bob.encode_state_as_update_v1(sv_dev))
        ).encode_v1(),
    )
    server.flush_device()
    assert server.device_text("pad") == ">> hello from alice"
    # A's outbox got the broadcast frame
    frames = server.drain(s_a)
    assert frames
    for f in frames:
        for msg in message_reader(f):
            if msg.kind == 0 and msg.body.tag == 2:
                alice.apply_update_v1(msg.body.payload)
    assert alice.get_text("text").get_string() == ">> hello from alice"


def test_device_authoritative_incremental_diff():
    """A reconnecting client with partial state gets only the missing
    blocks (diff vs its state vector, computed on device)."""
    server = DeviceSyncServer(n_docs=1, capacity=512, device_authoritative=True)
    writer = Doc(client_id=7)
    with writer.transact() as txn:
        writer.get_text("text").insert(txn, 0, "part one. ")
    s, greeting = server.connect("doc")
    server.receive(
        s,
        Message.sync(
            SyncMessage.update(writer.encode_state_as_update_v1())
        ).encode_v1(),
    )
    server.flush_device()

    # reader syncs fully now
    reader = Doc(client_id=8)
    sv0 = reader.state_vector()
    from ytpu.sync.protocol import message_reader

    reply = server.receive(s, Message.sync(SyncMessage.step1(sv0)).encode_v1())
    for msg in message_reader(reply):
        reader.apply_update_v1(msg.body.payload)
    assert reader.get_text("text").get_string() == "part one. "

    # writer adds more; reader reconnects with its current sv
    with writer.transact() as txn:
        t = writer.get_text("text")
        t.insert(txn, len(t.get_string()), "part two.")
    server.receive(
        s,
        Message.sync(
            SyncMessage.update(
                writer.encode_state_as_update_v1(
                    server.device_state_vector("doc")
                )
            )
        ).encode_v1(),
    )
    server.flush_device()
    reply = server.receive(
        s, Message.sync(SyncMessage.step1(reader.state_vector())).encode_v1()
    )
    for msg in message_reader(reply):
        reader.apply_update_v1(msg.body.payload)
    assert reader.get_text("text").get_string() == "part one. part two."


def test_multi_root_tenant_stays_device_resident():
    """A tenant whose clients use several named roots (text+map — the
    reference's normal doc shape, doc.rs:156-228) is served from the
    device batch: the first root maps onto the implicit branch, the
    second anchors through a BLOCK_ROOT_ANCHOR row, and a fresh replica
    syncing from device state reconstructs BOTH roots byte-exactly."""
    from ytpu.core import Doc
    from ytpu.core.state_vector import StateVector
    from ytpu.sync.device_server import DeviceSyncServer
    from ytpu.sync.protocol import Message, SyncMessage

    pod = DeviceSyncServer(n_docs=2, capacity=256, device_authoritative=True)
    session, _ = pod.connect_frames("app")

    c = Doc(client_id=31)
    log = []
    c.observe_update_v1(lambda p, o, t: log.append(p))
    with c.transact() as txn:
        c.get_text("body").insert(txn, 0, "words")
    with c.transact() as txn:
        c.get_map("meta").insert(txn, "title", "doc one")
    with c.transact() as txn:
        c.get_text("body").insert(txn, 5, "!")
    for p in log:
        pod.receive_frames(
            session, Message.sync(SyncMessage.update(p)).encode_v1()
        )
    pod.flush_device()
    assert "app" not in pod._host_tenants  # device-resident (VERDICT r3 #9)
    assert pod.device_text("app") == "words!"
    tree = pod.device_tree("app")
    assert tree["roots"]["meta"]["map"] == {"title": "doc one"}

    # a fresh client syncing sees BOTH roots intact
    session2, greeting = pod.connect_frames("app")
    step1 = Message.sync(
        SyncMessage.step1(StateVector({}))
    ).encode_v1()
    replies = pod.receive_frames(session2, step1)
    d = Doc(client_id=32)
    from ytpu.sync.protocol import message_reader

    for frame in list(greeting) + replies:
        for m in message_reader(frame):
            if m.kind == 0 and m.body.tag == 1:
                d.apply_update_v1(m.body.payload)
    assert d.get_text("body").get_string() == "words!"
    assert d.get_map("meta").to_json() == {"title": "doc one"}


def test_multi_root_tenant_checkpoint_roundtrip(tmp_path):
    """Multi-root tenants survive a checkpoint DEVICE-resident: anchor
    rows persist in the block state, the primary-root registry in the
    sidecar — a restored pod serves both roots from the batch."""
    from ytpu.core import Doc
    from ytpu.core.state_vector import StateVector
    from ytpu.models.checkpoint import load_device_server, save_device_server
    from ytpu.sync.device_server import DeviceSyncServer
    from ytpu.sync.protocol import Message, SyncMessage, message_reader

    pod = DeviceSyncServer(n_docs=2, capacity=256, device_authoritative=True)
    session, _ = pod.connect_frames("app")
    c = Doc(client_id=41)
    log = []
    c.observe_update_v1(lambda p, o, t: log.append(p))
    with c.transact() as txn:
        c.get_text("a").insert(txn, 0, "alpha")
    with c.transact() as txn:
        c.get_text("b").insert(txn, 0, "beta")
    for p in log:
        pod.receive_frames(
            session, Message.sync(SyncMessage.update(p)).encode_v1()
        )
    assert "app" not in pod._host_tenants

    save_device_server(str(tmp_path / "pod"), pod)
    restored = load_device_server(str(tmp_path / "pod"))
    assert "app" not in restored._host_tenants
    assert restored.device_text("app") == "alpha"
    assert restored.ingestor.primary_roots[restored.slot_of("app")] == "a"
    # a fresh replica syncs both roots from the restored device state
    s2, greeting = restored.connect_frames("app")
    replies = restored.receive_frames(
        s2, Message.sync(SyncMessage.step1(StateVector({}))).encode_v1()
    )
    d = Doc(client_id=42)
    for frame in list(greeting) + replies:
        for m in message_reader(frame):
            if m.kind == 0 and m.body.tag == 1:
                d.apply_update_v1(m.body.payload)
    assert d.get_text("a").get_string() == "alpha"
    assert d.get_text("b").get_string() == "beta"


def test_explicit_demotion_reclaims_device_slot():
    """The operational escape hatch (`_demote_to_host`) still moves a
    tenant to the host path losslessly and frees its slot for a new
    tenant — multi-root alone no longer triggers it."""
    from ytpu.core import Doc
    from ytpu.sync.device_server import DeviceSyncServer
    from ytpu.sync.protocol import Message, SyncMessage

    pod = DeviceSyncServer(n_docs=1, capacity=256, device_authoritative=True)
    session, _ = pod.connect_frames("multi")
    c = Doc(client_id=51)
    log = []
    c.observe_update_v1(lambda p, o, t: log.append(p))
    with c.transact() as txn:
        c.get_text("a").insert(txn, 0, "x")
    with c.transact() as txn:
        c.get_text("b").insert(txn, 0, "y")
    for p in log:
        pod.receive_frames(
            session, Message.sync(SyncMessage.update(p)).encode_v1()
        )
    assert "multi" not in pod._host_tenants  # multi-root stays on device
    pod._demote_to_host("multi")
    assert "multi" in pod._host_tenants
    doc = pod.tenant("multi").awareness.doc
    assert doc.get_text("a").get_string() == "x"
    assert doc.get_text("b").get_string() == "y"
    # the single slot was reclaimed: a NEW tenant fits a 1-slot pod
    s2, _ = pod.connect_frames("fresh")
    d = Doc(client_id=52)
    log2 = []
    d.observe_update_v1(lambda p, o, t: log2.append(p))
    with d.transact() as txn:
        d.get_text("t").insert(txn, 0, "fresh-tenant")
    for p in log2:
        pod.receive_frames(s2, Message.sync(SyncMessage.update(p)).encode_v1())
    pod.flush_device()
    assert pod.device_text("fresh") == "fresh-tenant"


def test_mirrored_server_checkpoint_keeps_host_docs(tmp_path):
    from ytpu.models.checkpoint import load_device_server, save_device_server
    from ytpu.sync.device_server import DeviceSyncServer

    pod = DeviceSyncServer(n_docs=2, capacity=256)  # mirrored mode
    doc = pod.doc("pad")
    with doc.transact() as txn:
        doc.get_text("t").insert(txn, 0, "persisted")
    pod.flush_device()
    save_device_server(str(tmp_path / "pod"), pod)
    restored = load_device_server(str(tmp_path / "pod"))
    assert not restored.device_authoritative
    assert restored.doc("pad").get_text("t").get_string() == "persisted"


def test_unflushed_queue_survives_checkpoint(tmp_path):
    from ytpu.core import Doc
    from ytpu.core.state_vector import StateVector
    from ytpu.models.checkpoint import load_device_server, save_device_server
    from ytpu.sync.device_server import DeviceSyncServer
    from ytpu.sync.protocol import Message, SyncMessage

    pod = DeviceSyncServer(n_docs=2, capacity=256, device_authoritative=True)
    session, _ = pod.connect_frames("pad")
    c = Doc(client_id=61)
    with c.transact() as txn:
        c.get_text("t").insert(txn, 0, "acked")
    upd = c.encode_state_as_update_v1(StateVector({}))
    pod.receive_frames(
        session, Message.sync(SyncMessage.update(upd)).encode_v1()
    )
    # NO flush_device() here: save must flush so the ack is durable
    save_device_server(str(tmp_path / "pod"), pod)
    restored = load_device_server(str(tmp_path / "pod"))
    assert restored.device_text("pad") == "acked"
