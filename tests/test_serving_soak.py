"""Multi-tenant serving soak subsystem (ISSUE-9): seeded scenario
replayability, soak byte parity across checkpoint/restore and live
rebalance, admission shed/overload-reply paths, the raw-ingest fast
lane, per-session net gauges, and a chaos variant arming transport
faults during a socket soak.

Suite-cost hygiene: every device-touching test here shares ONE
DeviceSyncServer shape family — (n_docs=4, capacity=256), the same
family tests/test_device_server.py compiles earlier in the run — and one
module-scoped clean soak whose report the parity tests compare against.
The CPU mini-soak is tens of sessions over a seconds-scale schedule.
"""

import asyncio

import numpy as np
import pytest

from ytpu.native import available as native_available
from ytpu.utils import metrics
from ytpu.utils.faults import faults

needs_native = pytest.mark.skipif(
    not native_available(), reason="native codec unavailable"
)

N_DOCS, CAPACITY = 4, 256
SEED = 5


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _cfg(**kw):
    from ytpu.serving import ScenarioConfig

    base = dict(
        n_tenants=3, n_sessions=8, events_per_session=8, seed=SEED
    )
    base.update(kw)
    return ScenarioConfig(**base)


def _fresh_server():
    from ytpu.sync.device_server import DeviceSyncServer

    return DeviceSyncServer(n_docs=N_DOCS, capacity=CAPACITY)


_CLEAN: dict = {}


def _clean_soak() -> dict:
    """One clean mini-soak per test process; the parity tests compare
    their digests against this run's (and pay no second compile)."""
    if not _CLEAN:
        from ytpu.serving import Scenario, SoakDriver

        driver = SoakDriver(_fresh_server(), Scenario(_cfg()), flush_every=4)
        _CLEAN["report"] = driver.run()
        _CLEAN["server"] = driver.server
    return _CLEAN


# ------------------------------------------------------------- scenario


def test_scenario_same_seed_is_byte_deterministic():
    from ytpu.serving import Scenario

    a, b = Scenario(_cfg()), Scenario(_cfg())
    assert a.digest() == b.digest()
    assert [e[1:] for e in a.events()] == [e[1:] for e in b.events()]
    # seed and round both perturb the stream
    assert a.digest() != Scenario(_cfg(seed=SEED + 1)).digest()
    assert a.digest() != a.with_round(1).digest()


def test_scenario_preserves_per_session_order_and_mixes_kinds():
    from ytpu.serving import Scenario

    sc = Scenario(_cfg(n_sessions=16, events_per_session=12))
    kinds = {e.kind for e in sc.events()}
    assert "apply" in kinds and len(kinds) >= 3, kinds
    # order within a session must match its script (CRDT causality)
    per = {}
    for ev in sc.events():
        per.setdefault(ev.session, []).append((ev.kind, ev.payload))
    for script in sc.sessions:
        assert per[script.sid] == script.events
    # Zipf skew: the hot tenant holds the plurality of sessions
    by_tenant = {}
    for s in sc.sessions:
        by_tenant[s.tenant] = by_tenant.get(s.tenant, 0) + 1
    assert by_tenant.get("tenant0", 0) == max(by_tenant.values())


# ------------------------------------------------------------ admission


def test_token_bucket_and_throttle_are_deterministic():
    from ytpu.serving import AdmissionController, QueueFull, RateLimited

    now = [0.0]
    slept = []

    def clock():
        return now[0]

    def sleep(s):
        slept.append(s)
        now[0] += s

    adm = AdmissionController(
        max_queue=2, rate=10.0, burst=2.0, policy="defer",
        clock=clock, sleep=sleep,
    )
    adm.admit("t", queue_depth=0)
    adm.admit("t", queue_depth=1)
    with pytest.raises(QueueFull):
        adm.admit("t", queue_depth=2)
    with pytest.raises(RateLimited) as ri:
        adm.admit("t", queue_depth=0)  # burst of 2 spent
    assert ri.value.retry_after_s == pytest.approx(0.1)
    now[0] += 0.1  # one token refills
    adm.admit("t", queue_depth=0)
    # producer-side throttle blocks (via injected sleep) instead of raising
    waited = adm.throttle(3)
    assert waited == pytest.approx(sum(slept))
    assert adm.throttle(0) == 0.0


def test_update_pipeline_staging_throttles_through_admission():
    """The backpressure hook (ISSUE-9): the staging producer consults the
    controller per chunk — asserted on the generator alone, no device
    dispatch."""
    from ytpu.models.batch_doc import BatchEncoder
    from ytpu.models.pipeline import UpdatePipeline

    class Recorder:
        def __init__(self):
            self.calls = []

        def throttle(self, n):
            self.calls.append(n)
            return 0.0

    from ytpu.core import Doc

    doc = Doc(client_id=3)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text("text")
    for i in range(5):
        with doc.transact() as txn:
            txt.insert(txn, 0, "ab")
    rec = Recorder()
    pipe = UpdatePipeline(
        BatchEncoder(), n_rows=4, n_dels=4, chunk_steps=2, admission=rec
    )
    pipe._staged_bytes = 0
    chunks = list(pipe._chunks(log))
    assert len(chunks) == 3  # 2+2+1 (padded tail)
    assert rec.calls == [2, 2, 1]


# ------------------------------------------------------------- the soak


@needs_native
def test_mini_soak_scores_and_matches_oracle():
    from ytpu.serving import Scenario

    bundle = _clean_soak()
    rep, server = bundle["report"], bundle["server"]
    assert rep["complete"] and rep["rounds"] == 1
    assert rep["applied"] > 0 and rep["updates_per_s"] > 0
    assert rep["mirror_parity"] is True
    # SLO fields: raw + floor-subtracted, adjusted never above raw
    assert rep["rtt_floor_ms"] >= 0
    for k in ("apply", "apply_e2e", "diff"):
        assert rep[f"{k}_p50_ms_adj"] <= rep[f"{k}_p50_ms"]
        assert rep[f"{k}_p99_ms_adj"] <= rep[f"{k}_p99_ms"]
    assert rep["apply_count"] > 0 and rep["diff_count"] > 0
    # final tenant states equal the scenario's CRDT merge oracle
    oracle = Scenario(_cfg()).expected_texts()
    for tenant, text in oracle.items():
        assert server.device_text(tenant) == text


@needs_native
def test_same_seed_soak_runs_land_byte_equal_states():
    from ytpu.serving import Scenario, SoakDriver

    clean = _clean_soak()["report"]
    again = SoakDriver(
        _fresh_server(), Scenario(_cfg()), flush_every=4
    ).run()
    assert again["scenario_digest"] == clean["scenario_digest"]
    assert again["state_digest"] == clean["state_digest"]


@needs_native
def test_checkpoint_restore_and_rebalance_keep_byte_parity(tmp_path):
    from ytpu.serving import Scenario, SoakDriver

    clean = _clean_soak()["report"]
    churn = SoakDriver(
        _fresh_server(),
        Scenario(_cfg()),
        flush_every=4,
        checkpoint_at=0.45,
        rebalance_at=0.7,
        ckpt_dir=str(tmp_path),
    ).run()
    assert churn["checkpoints"] == 1
    assert churn["rebalances"] == 1
    assert churn.get("rebalance_parity_failures", 0) == 0
    assert churn["state_digest"] == clean["state_digest"]
    assert metrics.counter("sync.rebalances").value >= 1


@needs_native
def test_live_rebalance_moves_slot_and_keeps_traffic_flowing():
    """Direct rebalance contract: the tenant's slot changes, its text
    survives byte-exact, and post-rebalance updates land in the NEW slot
    (the mirror observer resolves slots dynamically)."""
    from ytpu.core import Doc
    from ytpu.sync.protocol import Message, SyncMessage

    server = _fresh_server()
    sess, _ = server.connect_frames("mv")
    peer = Doc(client_id=77)
    txt = peer.get_text("text")
    with peer.transact() as txn:
        txt.insert(txn, 0, "before ")
    server.receive_frames(
        sess,
        Message.sync(
            SyncMessage.update(peer.encode_state_as_update_v1())
        ).encode_v1(),
    )
    server.flush_device()
    old = server.slot_of("mv")
    new = server.rebalance_tenant("mv")
    assert new != old and server.slot_of("mv") == new
    assert server.device_text("mv") == "before "
    with peer.transact() as txn:
        txt.insert(txn, len("before "), "after")
    sv = server.doc("mv").state_vector()
    server.receive_frames(
        sess,
        Message.sync(
            SyncMessage.update(peer.encode_state_as_update_v1(sv))
        ).encode_v1(),
    )
    server.flush_device()
    assert server.device_text("mv") == "before after"
    # explicit destination: the claimed slot must leave the free list,
    # or a later tenant's _assign_slot would share it (allocator hole)
    back = server.rebalance_tenant("mv", to_slot=old)
    assert back == old and server.slot_of("mv") == old
    assert server.device_text("mv") == "before after"
    server.connect_frames("other")
    assert server.slot_of("other") != old


# ----------------------------------------------- admission × the server


@needs_native
def test_admission_defer_replies_busy_and_converges():
    from ytpu.serving import AdmissionController, Scenario, SoakDriver

    clean = _clean_soak()["report"]
    busy = SoakDriver(
        _fresh_server(),
        Scenario(_cfg()),
        admission=AdmissionController(max_queue=2, policy="defer"),
        flush_every=64,  # queues pile up → the bound trips
    ).run()
    assert busy["busy_replies"] >= 1
    assert busy["admission"]["rejected_queue_full"] >= 1
    assert metrics.counter("sync.busy_replies").value >= 1
    # defer loses nothing: retries drain and parity holds
    assert busy["state_digest"] == clean["state_digest"]


@needs_native
def test_admission_shed_kills_session_with_attribution():
    from ytpu.serving import AdmissionController, Scenario, SoakDriver

    dropped = metrics.counter(
        "net.sessions_dropped", labelnames=("reason",)
    ).labels("shed")
    before = dropped.value
    rep = SoakDriver(
        _fresh_server(),
        Scenario(_cfg()),
        admission=AdmissionController(max_queue=1, policy="shed"),
        flush_every=64,
    ).run()
    assert dropped.value > before
    # shed is lossy by design: the server applied fewer updates than the
    # driver submitted (refusals kill the session instead of replying)
    assert rep["applied_server"] < rep["applied"]


@needs_native
def test_injected_admission_reject_exercises_busy_path():
    from ytpu.serving import AdmissionController, Scenario, SoakDriver

    clean = _clean_soak()["report"]
    faults.arm("admission.reject", n=2)
    rep = SoakDriver(
        _fresh_server(),
        Scenario(_cfg()),
        admission=AdmissionController(max_queue=None, policy="defer"),
        flush_every=4,
    ).run()
    assert rep["busy_replies"] >= 2
    assert rep["admission"]["rejected_injected"] >= 2
    assert rep["state_digest"] == clean["state_digest"]


@needs_native
def test_session_kill_fault_reconnects_with_parity():
    from ytpu.serving import Scenario, SoakDriver

    clean = _clean_soak()["report"]
    faults.arm("session.kill", after=5, n=3)
    rep = SoakDriver(
        _fresh_server(), Scenario(_cfg()), flush_every=4
    ).run()
    assert rep["session_kills"] == 3
    assert rep["state_digest"] == clean["state_digest"]


# --------------------------------------- diff path through the pipeline


@needs_native
def test_soak_diff_path_through_pipeline_matches_serial():
    """ISSUE-10: in the device-authoritative serving mode — the one
    where the device batch answers SyncStep1s — every soak diff routes
    through the encode `DiffPipeline`, the run lands the SAME state
    digest as the mirrored clean run (the pipeline produced the pinned
    digest), and re-answering each tenant's step1 is byte-equal to the
    serial `finish_encode_diff_batch` path.  (Mirrored-mode soaks keep
    answering diffs from the authoritative HOST doc by design — their
    `diff_pipeline_runs` reads 0.)"""
    import jax.numpy as jnp

    from ytpu.core import StateVector
    from ytpu.models import batch_doc as bd
    from ytpu.serving import Scenario, SoakDriver
    from ytpu.sync.device_server import DeviceSyncServer

    clean = _clean_soak()["report"]
    assert clean["diff_pipeline_runs"] == 0  # mirrored mode: host path
    driver = SoakDriver(
        DeviceSyncServer(
            n_docs=N_DOCS, capacity=CAPACITY, device_authoritative=True
        ),
        Scenario(_cfg()),
        flush_every=4,
    )
    rep = driver.run()
    server = driver.server
    assert rep["diffs"] > 0
    # each diff event (plus the RTT idle-echo probes) ran the pipeline,
    # and none of them had to demote off the native batched path
    assert rep["diff_pipeline_runs"] >= rep["diffs"]
    assert rep["encode_demotions"] == 0
    assert rep["state_digest"] == clean["state_digest"]
    for t in sorted(server.tenants):
        try:
            slot = server.slot_of(t)
        except KeyError:
            continue  # host-resident tenant: no device diff to compare
        piped = server.device_encode_diff(t, StateVector())
        remote, n_clients = server._remote_matrix([(slot, StateVector())])
        ship, offsets, _sv, deleted = bd.encode_diff_batch(
            server.ingestor.state, jnp.asarray(remote), n_clients
        )
        serial = bd.finish_encode_diff_batch(
            server.ingestor.state,
            [slot],
            ship,
            offsets,
            deleted,
            server.ingestor.enc,
            payloads=server.ingestor.payloads,
            root_name=server._root_names.get(t),
        )[0]
        assert piped == server._merge_pending(slot, serial), t


@needs_native
def test_device_encode_diff_many_fanout_parity():
    """The batched fan-out entry answers many tenants in one pipelined
    pass, byte-equal to the per-tenant path; duplicate tenants are
    rejected (they would collide on the slot's remote-clock row)."""
    from ytpu.core import StateVector

    server = _clean_soak()["server"]
    tenants = [t for t in sorted(server.tenants) if t in server._slot_of]
    assert len(tenants) >= 2
    many = server.device_encode_diff_many(
        [(t, StateVector()) for t in tenants]
    )
    for t, payload in zip(tenants, many):
        assert payload == server.device_encode_diff(t, StateVector()), t
    with pytest.raises(ValueError, match="one request per tenant"):
        server.device_encode_diff_many(
            [(tenants[0], StateVector()), (tenants[0], StateVector())]
        )


# -------------------------------------------------- chaos over sockets


@needs_native
def test_chaos_soak_survives_transport_faults():
    """The ISSUE-9 chaos variant: the scenario over real sockets with
    `net.drop`/`net.delay` armed mid-soak (the ISSUE-6 sites).  Scores
    survivability: every fault fires, the accept loop outlives them, and
    the mirrored device batch stays consistent with the host docs for
    whatever traffic did land."""
    from ytpu.serving import Scenario, run_soak_tcp

    server = _fresh_server()
    armed = []  # per-spec fired counters: reset-proof assertion surface

    def arm():
        armed.append(faults.arm("net.drop", after=3, n=2))
        armed.append(faults.arm("net.delay", ms=5, n=4))

    counts = run_soak_tcp(
        server,
        Scenario(_cfg(n_sessions=6, events_per_session=6)),
        arm=arm,
        budget_s=20.0,
        frame_deadline=1.0,
    )
    faults.clear()
    assert counts["survived"] and counts["sent"] > 0
    assert sum(s.fired for s in armed) >= 2, (counts, armed)
    server.flush_device()
    for t in sorted(server.tenants):
        host = server.doc(t).get_text("text").get_string()
        assert server.device_text(t) == host


def test_net_session_gauges_track_active_and_bad_frame_drops():
    from ytpu.core import Doc
    from ytpu.sync import net as net_mod
    from ytpu.sync.net import SyncClient, serve, write_frame
    from ytpu.sync.server import SyncServer

    # the transport's OWN cached series (module-level in net.py): a
    # fresh registry lookup would diverge after any metrics.reset()
    # earlier in the suite (test_metrics_trace sorts before this file)
    active = net_mod._SESSIONS_ACTIVE
    bad = net_mod._SESSIONS_DROPPED.labels("bad_frame")

    async def main():
        base_active = active.value
        base_bad = bad.value
        server = SyncServer()
        srv, port = await serve(server, idle_flush=0.05)
        a = SyncClient(Doc(client_id=61))
        await a.connect("127.0.0.1", port, "room")
        await a.pump(max_frames=2, timeout=0.3)
        assert active.value == base_active + 1
        # a second peer sends protocol garbage after its hello: its
        # session drops with reason=bad_frame, the first session lives
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        write_frame(writer, b"room")
        write_frame(writer, b"\xff\xff\xff\xff")
        await writer.drain()
        for _ in range(50):
            if bad.value > base_bad:
                break
            await asyncio.sleep(0.05)
        assert bad.value > base_bad
        writer.close()
        await a.close()
        for _ in range(50):
            if active.value == base_active:
                break
            await asyncio.sleep(0.05)
        assert active.value == base_active
        srv.close()
        await srv.wait_closed()

    asyncio.run(main())


# ------------------------------------------- raw-ingest fast lane (ROADMAP 2)


@needs_native
def test_ingest_fast_lane_raw_matches_packed_byte_exactly():
    """The ingest fast lane ships raw concatenated wire bytes + offsets
    and gathers the lane matrix ON DEVICE (`gather_raw_lanes`): final
    device state must be byte-identical to the host-packed path, with
    the fast lane proven to have actually run."""
    import jax

    from ytpu.core import Doc
    from ytpu.models.batch_doc import get_string
    from ytpu.models.ingest import BatchIngestor

    doc = Doc(client_id=1)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text("text")
    for i in range(8):
        with doc.transact() as txn:
            if i % 3 == 2:
                txt.remove_range(txn, 0, 1)
            else:
                txt.insert(txn, 0, f"w{i}")
    expect = txt.get_string()
    states = {}
    for mode in ("raw", "packed"):
        ing = BatchIngestor(2, CAPACITY, ingest=mode)
        for p in log:
            ing.apply_bytes([p, None])
        assert ing.fast_docs > 0, (mode, ing.slow_docs)
        assert get_string(ing.state, 0, ing.payloads) == expect
        states[mode] = ing.state
    for a, b in zip(
        jax.tree_util.tree_leaves(states["raw"]),
        jax.tree_util.tree_leaves(states["packed"]),
    ):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_ingest_rejects_unknown_mode():
    from ytpu.models.ingest import BatchIngestor

    with pytest.raises(ValueError, match="ingest must be"):
        BatchIngestor(2, 64, ingest="zip")


@needs_native
def test_decode_v2_raw_stream_parity_end_to_end():
    """V2 raw ingestion end-to-end through the DEVICE decoder:
    `decode_updates_v2_raw` (flat arena + on-device gather) must produce
    the identical decoded stream and flags as `decode_updates_v2` over
    the host-packed matrix (ISSUE-9 satellite; the pack-level byte
    parity lives in test_async_raw_ingest)."""
    import jax

    from ytpu.core import Doc, Update
    from ytpu.ops.decode_v2 import (
        decode_updates_v2,
        decode_updates_v2_raw,
        pack_updates_v2,
    )
    from ytpu.ops.decode_v2 import pack_updates_v2_raw

    import jax.numpy as jnp

    doc = Doc(client_id=5)
    log = []
    doc.observe_update_v1(lambda p, o, t: log.append(p))
    txt = doc.get_text("text")
    for i in range(4):
        with doc.transact() as txn:
            txt.insert(txn, i, "abcd"[i])
    v2 = [Update.decode_v1(p).encode_v2() for p in log]
    buf, lens, spans, side = pack_updates_v2(v2)
    packed_stream, packed_flags = decode_updates_v2(
        jnp.asarray(buf), jnp.asarray(lens), spans,
        max_rows=4, max_dels=4, sidecar=side,
    )
    wire, offs, row_lens, rlens, rspans, rside, width = pack_updates_v2_raw(v2)
    raw_stream, raw_flags = decode_updates_v2_raw(
        wire, offs, row_lens, rlens, rspans, width,
        max_rows=4, max_dels=4, sidecar=rside,
    )
    assert (np.asarray(raw_flags) == np.asarray(packed_flags)).all()
    for a, b in zip(
        jax.tree_util.tree_leaves(raw_stream),
        jax.tree_util.tree_leaves(packed_stream),
    ):
        assert (np.asarray(a) == np.asarray(b)).all()
