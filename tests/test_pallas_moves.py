"""Fused-kernel parity for move ranges (the last row kind the Pallas path
excluded — VERDICT r2 #4).

Each scenario builds a move-bearing update stream with host docs, replays
it through (a) the XLA batched engine (the established spec,
tests/test_batch_move.py) and (b) `apply_update_stream_fused`, and
asserts identical rendered sequences plus identical move ownership.
Interpreter mode on the CPU mesh, like tests/test_pallas_kernel.py.
"""

import numpy as np
import pytest

from ytpu.core import Doc, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_stream,
    get_values,
    init_state,
)
from ytpu.ops.integrate_kernel import apply_update_stream_fused

from _fused_interpret import run_or_skip


def capture(doc: Doc):
    log = []
    doc.observe_update_v1(lambda payload, origin, txn: log.append(payload))
    return log


def seeded_array(values, client_id=1):
    doc = Doc(client_id=client_id)
    log = capture(doc)
    arr = doc.get_array("a")
    with doc.transact() as txn:
        for v in values:
            arr.push_back(txn, v)
    return doc, arr, log


def run_both(update_stream, n_docs=2, capacity=128, rows=6, dels=4):
    enc = BatchEncoder(root_name="a")
    steps = [enc.build_step(Update.decode_v1(p), rows, dels) for p in update_stream]
    stream = BatchEncoder.stack_steps(steps)
    rank = enc.interner.rank_table()
    # fused (skippable) lane first: a skip never pays the XLA compile
    fused = run_or_skip(lambda: apply_update_stream_fused(
        init_state(n_docs, capacity), stream, rank, d_block=n_docs, interpret=True
    ))
    xla = apply_update_stream(init_state(n_docs, capacity), stream, rank)
    return xla, fused, enc


def assert_move_parity(update_stream, **kw):
    host = Doc(client_id=0xDEAD)
    for p in update_stream:
        host.apply_update_v1(p)
    expect = host.get_array("a").to_json()
    xla, fused, enc = run_both(update_stream, **kw)
    assert int(np.asarray(fused.error).max()) == 0
    for d in (0, xla.start.shape[0] - 1):
        assert get_values(fused, d, enc.payloads) == expect
        assert get_values(xla, d, enc.payloads) == expect
    # ownership columns must agree exactly with the XLA recompute
    np.testing.assert_array_equal(
        np.asarray(fused.blocks.moved), np.asarray(xla.blocks.moved)
    )
    np.testing.assert_array_equal(
        np.asarray(fused.blocks.deleted), np.asarray(xla.blocks.deleted)
    )
    return expect


def test_fused_collapsed_move():
    doc, arr, log = seeded_array([0, 1, 2, 3, 4])
    with doc.transact() as txn:
        arr.move_to(txn, 1, 4)
    assert arr.to_json() == [0, 2, 3, 1, 4]
    assert_move_parity(log)


def test_fused_range_move_backward():
    doc, arr, log = seeded_array(list(range(6)))
    with doc.transact() as txn:
        arr.move_range_to(txn, 3, 4, 1)
    assert arr.to_json() == [0, 3, 4, 1, 2, 5]
    assert_move_parity(log)


def test_fused_insert_into_moved_range():
    doc, arr, log = seeded_array(list(range(5)))
    with doc.transact() as txn:
        arr.move_range_to(txn, 2, 3, 0)
    with doc.transact() as txn:
        arr.insert(txn, 2, ["x"])
    assert_move_parity(log)


def test_fused_concurrent_moves_both_orders():
    a, arr_a, log_a = seeded_array([0, 1, 2, 3, 4], client_id=1)
    seed = list(log_a)
    b = Doc(client_id=2)
    log_b = capture(b)
    for p in seed:
        b.apply_update_v1(p)
    with a.transact() as txn:
        arr_a.move_to(txn, 1, 4)
    mv_a = log_a[-1]
    arr_b = b.get_array("a")
    with b.transact() as txn:
        arr_b.move_to(txn, 1, 3)
    mv_b = log_b[-1]
    for order in ([mv_a, mv_b], [mv_b, mv_a]):
        assert_move_parity(seed + order)


def test_fused_move_delete_releases_range():
    """Deleting the move item releases its claims (recompute via the
    delete-range dirty flag)."""
    doc, arr, log = seeded_array(list(range(5)))
    with doc.transact() as txn:
        arr.move_to(txn, 0, 4)
    with doc.transact() as txn:
        # deleting the element that was moved tombstones the move row too
        arr.remove_range(txn, 3, 1)
    assert_move_parity(log)


def test_fused_branch_scoped_move():
    """Move from index 0: branch-scoped (None) start bound."""
    doc, arr, log = seeded_array([0, 1, 2, 3])
    with doc.transact() as txn:
        arr.move_to(txn, 0, 3)
    assert_move_parity(log)


def test_fused_mixed_stream_with_text_docs():
    """A move-bearing stream interleaved with plain edits keeps the
    non-move docs' fast path intact (same batch, several docs)."""
    doc, arr, log = seeded_array(list(range(4)))
    with doc.transact() as txn:
        arr.move_to(txn, 3, 0)
    with doc.transact() as txn:
        arr.push_back(txn, 99)
    assert_move_parity(log, n_docs=4, capacity=128)


def test_fused_fuzz_random_moves():
    import random

    rng = random.Random(5)
    doc, arr, log = seeded_array(list(range(8)))
    for _ in range(12):
        n = len(arr)
        with doc.transact() as txn:
            r = rng.random()
            if r < 0.5 and n >= 2:
                i = rng.randrange(n)
                j = rng.randrange(n + 1)
                arr.move_to(txn, i, j)
            elif r < 0.75:
                arr.insert(txn, rng.randrange(n + 1), [rng.randrange(100)])
            elif n > 2:
                arr.remove_range(txn, rng.randrange(n - 1), 1)
    assert_move_parity(log, capacity=256, rows=8, dels=6)
