"""Sequence parallelism: sp-sharded single-document sequence kernel.

Covers SURVEY.md §5.7 / §2's SP axis: contiguous chunk partitioning over an
8-device mesh, prefix-sum index routing, boundary-spanning deletes, and the
ppermute halo exchange that rebalances shard load. Oracle = plain Python
string splicing (the device path models the sequence kernel, not the wire).
"""

import random
import string

import jax
import numpy as np
import pytest

from ytpu.parallel.seq_shard import (
    HALO,
    SHARD_MAP_AVAILABLE,
    apply_ops_sharded,
    build_op_stream,
    init_sharded,
    make_sp_mesh,
    read_text,
)

N_SHARDS = 8


@pytest.fixture(scope="module")
def mesh():
    if not SHARD_MAP_AVAILABLE:
        # environmental, same spirit as tests/_fused_interpret: this jax
        # build exposes neither jax.shard_map nor the experimental entry
        # point, so the sp kernel cannot dispatch at all — skip, don't fail
        pytest.skip(
            "shard_map unavailable in this jax build "
            "(no jax.shard_map / jax.experimental.shard_map)"
        )
    if len(jax.devices()) < N_SHARDS:
        pytest.skip(f"needs {N_SHARDS} devices")
    return make_sp_mesh(N_SHARDS)


def oracle(ops):
    buf = []
    for tag, p, arg in ops:
        if tag == "i":
            for i, c in enumerate(str(arg)):
                buf.insert(p + i, c)
        else:
            del buf[p : p + arg]
    return "".join(buf)


def replay(ops, mesh, cap=512, rebalance_every=64):
    state = init_sharded(N_SHARDS, cap)
    state = apply_ops_sharded(state, build_op_stream(ops), mesh, rebalance_every)
    assert int(np.asarray(state.error).max()) == 0, "shard overflow"
    return state


def test_basic_insert_delete(mesh):
    ops = [
        ("i", 0, "hello world"),
        ("i", 5, ","),
        ("d", 0, 6),
        ("i", 0, "W"),
        ("d", 1, 1),
    ]
    state = replay(ops, mesh)
    assert read_text(state) == oracle(ops)


def test_random_ops_match_oracle(mesh):
    rng = random.Random(1234)
    ops, length = [], 0
    for _ in range(400):
        if length > 10 and rng.random() < 0.3:
            p = rng.randint(0, length - 1)
            n = rng.randint(1, min(10, length - p))
            ops.append(("d", p, n))
            length -= n
        else:
            w = "".join(
                rng.choice(string.ascii_lowercase)
                for _ in range(rng.randint(1, 40))  # >max_ins forces chunking
            )
            ops.append(("i", rng.randint(0, length), w))
            length += len(w)
    state = replay(ops, mesh, cap=2048)
    assert read_text(state) == oracle(ops)


def test_skewed_prepends_balance_via_halo_exchange(mesh):
    """All inserts land at position 0; without the ppermute halo exchange
    shard 0 would overflow (2400 chars > cap=512)."""
    ops = [("i", 0, "abcdefgh") for _ in range(300)]
    state = replay(ops, mesh, cap=512, rebalance_every=32)
    lengths = np.asarray(state.length)
    assert read_text(state) == oracle(ops)
    assert lengths.sum() == 2400
    # balanced within one halo step of the mean
    assert lengths.max() - lengths.min() <= HALO


def test_boundary_spanning_delete(mesh):
    """A delete covering several shards' intervals applies distributively."""
    # appends are a hot-shard workload: keep per-chunk inflow (8 ops x 30
    # chars) under the halo bandwidth (HALO=256 chars/step)
    ops = [("i", 30 * i, "x" * 30) for i in range(80)]  # 2400 chars
    state = replay(ops, mesh, cap=512, rebalance_every=8)
    total = int(np.asarray(state.length).sum())
    del_ops = [("d", 100, total - 200)]  # spans ~all interior shards
    full = ops + del_ops
    state = replay(full, mesh, cap=512, rebalance_every=8)
    got = read_text(state)
    assert got == oracle(full)
    assert len(got) == 200


def test_editing_trace_prefix(mesh):
    """Replay a real B4 editing-trace prefix when the asset is present."""
    try:
        from bench import TRACE_PATH, load_b4_ops

        ops = load_b4_ops(500)
    except (ImportError, FileNotFoundError, OSError):
        pytest.skip("B4 trace asset unavailable")
    state = replay(ops, mesh, cap=2048, rebalance_every=64)
    assert read_text(state) == oracle(ops)
