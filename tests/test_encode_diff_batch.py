"""Batched device-side diff encoding (encode_diff_batch, north-star #2)."""

import numpy as np
import pytest

from ytpu.core import Doc, StateVector, Update
from ytpu.models.batch_doc import (
    BatchEncoder,
    apply_update_batch,
    encode_diff_batch,
    finish_encode_diff,
    init_state,
)


def build_device_docs(edit_lists, capacity=128):
    """Host docs per slot + a device batch mirroring them."""
    docs = []
    logs = []
    for i, edits in enumerate(edit_lists):
        d = Doc(client_id=i + 1)
        log = []
        d.observe_update_v1(lambda p, o, t, log=log: log.append(p))
        t = d.get_text("text")
        for pos, chunk in edits:
            with d.transact() as txn:
                t.insert(txn, pos, chunk)
        docs.append(d)
        logs.append(log)
    enc = BatchEncoder()
    state = init_state(len(docs), capacity)
    max_steps = max(len(lg) for lg in logs)
    for step in range(max_steps):
        updates = [
            Update.decode_v1(lg[step]) if step < len(lg) else None for lg in logs
        ]
        batch = enc.build_batch(updates, n_rows=2, n_dels=2)
        state = apply_update_batch(state, batch, enc.interner.rank_table())
    return docs, state, enc


def test_diff_selection_and_bytes():
    docs, state, enc = build_device_docs(
        [
            [(0, "hello"), (5, " world")],
            [(0, "doc-two")],
            [(0, "abc"), (0, "xyz")],
        ]
    )
    n_clients = max(8, len(enc.interner))
    # remote knows nothing: full state ships
    remote = np.zeros((len(docs), n_clients), dtype=np.int32)
    ship, offsets, local_sv, deleted = jax.tree_util.tree_map(
        np.asarray, encode_diff_batch(state, remote, n_clients)
    )
    for i, doc in enumerate(docs):
        payload = finish_encode_diff(state, i, ship, offsets, deleted, enc)
        replica = Doc(client_id=42)
        replica.apply_update_v1(payload)
        assert replica.get_text("text").get_string() == doc.get_text(
            "text"
        ).get_string(), f"doc {i}"


def test_diff_respects_remote_state():
    docs, state, enc = build_device_docs([[(0, "base"), (4, "-tail")]])
    doc = docs[0]
    # a remote that already has "base": only the tail must ship
    remote_doc = Doc(client_id=9)
    # replay just the first update
    base_update = doc.encode_state_as_update_v1(StateVector())
    n_clients = max(8, len(enc.interner))
    client_idx = enc.interner.to_idx[doc.client_id]
    remote = np.zeros((1, n_clients), dtype=np.int32)
    remote[0, client_idx] = 4  # has "base"
    ship, offsets, local_sv, deleted = jax.tree_util.tree_map(
        np.asarray, encode_diff_batch(state, remote, n_clients)
    )
    payload = finish_encode_diff(state, 0, ship, offsets, deleted, enc)
    # ship to a remote constructed from the first four clock units
    remote_doc.apply_update_v1(base_update)  # simulate having everything...
    fresh = Doc(client_id=11)
    u = Update.decode_v1(payload)
    blocks = [b for dq in u.blocks.values() for b in dq]
    # only the missing suffix is encoded
    assert all(b.id.clock >= 4 for b in blocks)
    total = sum(b.len for b in blocks)
    assert total == 5  # "-tail"


def test_diff_batch_scales_per_doc_independently():
    docs, state, enc = build_device_docs(
        [[(0, "aaaa")], [(0, "bbbbbb")], [(0, "c")], [(0, "dddd"), (0, "!")]]
    )
    n_clients = max(8, len(enc.interner))
    remote = np.zeros((len(docs), n_clients), dtype=np.int32)
    # doc 1's remote is fully caught up
    remote[1, enc.interner.to_idx[2]] = 6
    ship, offsets, local_sv, deleted = jax.tree_util.tree_map(
        np.asarray, encode_diff_batch(state, remote, n_clients)
    )
    assert ship[1].sum() == 0  # nothing to ship for doc 1
    assert ship[0].sum() > 0 and ship[3].sum() > 0
    # local SV matches host docs
    for i, doc in enumerate(docs):
        for client, clock in doc.state_vector().clocks.items():
            assert local_sv[i, enc.interner.to_idx[client]] == clock


import jax  # noqa: E402  (used by tree_map above)
